"""Checkpoint/resume reproducibility: a resumed run must replay the SAME
shuffled data order as an uninterrupted run (SURVEY §7 step 3 — the data
iterator is part of the checkpoint, not just params/opt state)."""
import os

import numpy as np
import pytest

from analytics_zoo_tpu.common.triggers import MaxEpoch, MaxIteration, SeveralIteration
from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
from analytics_zoo_tpu.keras.layers import Activation, Dense
from analytics_zoo_tpu.common.config import global_config


def _data(n=32):
    rs = np.random.RandomState(0)
    return (rs.randn(n, 6).astype(np.float32),
            rs.randint(0, 2, n).astype(np.float32))


def _estimator():
    model = Sequential([Dense(8, name="d1"), Activation("relu"),
                        Dense(2, name="d2")])
    return Estimator(model=model,
                     loss_fn=objectives.get("sparse_categorical_crossentropy"),
                     optimizer=optimizers.SGD(0.05))


def _fs():
    x, y = _data()
    return FeatureSet.from_ndarrays(x, y, shuffle=True, seed=7)


class TestResumeReproducibility:
    def test_epoch_boundary_resume_matches_straight_run(self, tmp_path):
        # straight run: 4 epochs
        est_a = _estimator()
        ra = est_a.train(_fs(), batch_size=8, epochs=4)

        # interrupted run: 2 epochs, checkpoint, then a FRESH estimator
        # resumes from the snapshot with a FRESH FeatureSet
        ck = str(tmp_path / "ck")
        est_b = _estimator()
        est_b.set_checkpoint(ck)
        rb = est_b.train(_fs(), batch_size=8, epochs=2)
        snaps = sorted(os.listdir(ck))
        assert snaps, "no snapshot written"

        est_c = _estimator()
        est_c.set_checkpoint(ck)
        est_c.load_checkpoint(est_c._latest_snapshot())
        assert est_c.epoch == 3 and est_c.global_step == 8
        rc = est_c.train(_fs(), batch_size=8, epochs=4)

        # identical loss trajectory: epochs 3-4 of the straight run
        np.testing.assert_allclose(ra["loss_history"][8:],
                                   rc["loss_history"], rtol=0, atol=0)
        # identical final params, bit for bit
        pa, pc = est_a.get_params(), est_c.get_params()
        np.testing.assert_array_equal(pa["d1"]["kernel"], pc["d1"]["kernel"])
        np.testing.assert_array_equal(pa["d2"]["kernel"], pc["d2"]["kernel"])

    def test_mid_epoch_resume_matches_straight_run(self, tmp_path):
        est_a = _estimator()
        ra = est_a.train(_fs(), batch_size=8, end_trigger=MaxEpoch(3))

        # stop mid-epoch-2 (iteration 6 of 12), snapshotting there
        ck = str(tmp_path / "ck")
        est_b = _estimator()
        est_b.set_checkpoint(ck)
        est_b.train(_fs(), batch_size=8, end_trigger=MaxIteration(6),
                    checkpoint_trigger=SeveralIteration(6))
        est_c = _estimator()
        est_c.load_checkpoint(os.path.join(ck, "snapshot-6"))
        assert est_c.global_step == 6
        rc = est_c.train(_fs(), batch_size=8, end_trigger=MaxEpoch(3))

        np.testing.assert_allclose(ra["loss_history"][6:],
                                   rc["loss_history"], rtol=0, atol=0)
        np.testing.assert_array_equal(est_a.get_params()["d2"]["kernel"],
                                      est_c.get_params()["d2"]["kernel"])

    def test_corrupt_checkpoint_rejected(self, tmp_path):
        import orbax.checkpoint as ocp
        bad = str(tmp_path / "bad")
        ocp.PyTreeCheckpointer().save(bad, {"params": {"d1": np.zeros(3)}})
        est = _estimator()
        with pytest.raises(ValueError, match="not an estimator snapshot"):
            est.load_checkpoint(bad)

    def test_structure_mismatch_rejected(self, tmp_path):
        ck = str(tmp_path / "ck")
        est_a = _estimator()
        est_a.set_checkpoint(ck)
        est_a.train(_fs(), batch_size=8, epochs=1)
        # a DIFFERENT architecture must refuse the snapshot once initialized
        other = Sequential([Dense(4, name="other1"), Dense(2, name="other2")])
        est_b = Estimator(
            model=other,
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.SGD(0.1))
        x, y = _data()
        est_b.train(FeatureSet.from_ndarrays(x, y), batch_size=8, epochs=1)
        with pytest.raises(ValueError, match="structure does not match"):
            est_b.load_checkpoint(est_a._latest_snapshot())


class TestElasticRetry:
    """Fault injection for the retry-from-checkpoint loop (reference
    InternalDistriOptimizer retry semantics, Topology.scala:1180-1262)."""

    def test_recovers_from_transient_step_failure(self, ctx, tmp_path):
        rs = np.random.RandomState(0)
        x = rs.rand(256, 4).astype(np.float32)
        y = (x.sum(1) > 2).astype(np.float32)
        est = Estimator(
            model=Sequential([Dense(8, activation="relu"), Dense(2)]),
            loss_fn=objectives.get(
                "sparse_categorical_crossentropy_from_logits"),
            optimizer=optimizers.Adam(1e-2))
        est.set_checkpoint(str(tmp_path), SeveralIteration(2))
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=64, epochs=1)  # 4 its; snapshots at 2 and 4

        # inject: the next dispatched step blows up ONCE (transient chip /
        # tunnel failure), later steps succeed
        real_step = est._train_step
        state = {"failed": False}

        def flaky_step(*args):
            if not state["failed"] and est.global_step == 5:
                state["failed"] = True
                raise RuntimeError("injected transient step failure")
            return real_step(*args)

        est._train_step = flaky_step
        out = est.train(fs, batch_size=64, epochs=2)
        assert state["failed"], "fault was never injected"
        # training completed both epochs after recovering from the snapshot
        # (est.epoch is the 1-based NEXT epoch: 3 == two epochs done)
        assert est.epoch == 3
        assert est.global_step == 8  # no steps lost or duplicated
        assert np.isfinite(out["loss_history"]).all()

    def test_retry_budget_exhausts(self, ctx, tmp_path):
        rs = np.random.RandomState(0)
        x = rs.rand(128, 4).astype(np.float32)
        y = rs.rand(128, 1).astype(np.float32)
        est = Estimator(model=Sequential([Dense(4), Dense(1)]),
                        loss_fn=objectives.get("mse"),
                        optimizer=optimizers.SGD(0.01))
        est.set_checkpoint(str(tmp_path), SeveralIteration(1))
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=64, epochs=1)

        calls = {"n": 0}

        def always_fails(*args):
            calls["n"] += 1
            raise RuntimeError("permanent failure")

        est._train_step = always_fails
        budget = int(global_config().get("failure.retry_times"))
        with pytest.raises(RuntimeError, match="permanent failure"):
            est.train(fs, batch_size=64, epochs=2)
        # the loop consumed its whole retry budget before surfacing: one
        # initial attempt + `budget` retries from the snapshot
        assert calls["n"] == budget + 1
