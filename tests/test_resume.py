"""Checkpoint/resume reproducibility: a resumed run must replay the SAME
shuffled data order as an uninterrupted run (SURVEY §7 step 3 — the data
iterator is part of the checkpoint, not just params/opt state)."""
import os

import numpy as np
import pytest

from analytics_zoo_tpu.common.triggers import MaxEpoch, MaxIteration, SeveralIteration
from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
from analytics_zoo_tpu.keras.layers import Activation, Dense


def _data(n=32):
    rs = np.random.RandomState(0)
    return (rs.randn(n, 6).astype(np.float32),
            rs.randint(0, 2, n).astype(np.float32))


def _estimator():
    model = Sequential([Dense(8, name="d1"), Activation("relu"),
                        Dense(2, name="d2")])
    return Estimator(model=model,
                     loss_fn=objectives.get("sparse_categorical_crossentropy"),
                     optimizer=optimizers.SGD(0.05))


def _fs():
    x, y = _data()
    return FeatureSet.from_ndarrays(x, y, shuffle=True, seed=7)


class TestResumeReproducibility:
    def test_epoch_boundary_resume_matches_straight_run(self, tmp_path):
        # straight run: 4 epochs
        est_a = _estimator()
        ra = est_a.train(_fs(), batch_size=8, epochs=4)

        # interrupted run: 2 epochs, checkpoint, then a FRESH estimator
        # resumes from the snapshot with a FRESH FeatureSet
        ck = str(tmp_path / "ck")
        est_b = _estimator()
        est_b.set_checkpoint(ck)
        rb = est_b.train(_fs(), batch_size=8, epochs=2)
        snaps = sorted(os.listdir(ck))
        assert snaps, "no snapshot written"

        est_c = _estimator()
        est_c.set_checkpoint(ck)
        est_c.load_checkpoint(est_c._latest_snapshot())
        assert est_c.epoch == 3 and est_c.global_step == 8
        rc = est_c.train(_fs(), batch_size=8, epochs=4)

        # identical loss trajectory: epochs 3-4 of the straight run
        np.testing.assert_allclose(ra["loss_history"][8:],
                                   rc["loss_history"], rtol=0, atol=0)
        # identical final params, bit for bit
        pa, pc = est_a.get_params(), est_c.get_params()
        np.testing.assert_array_equal(pa["d1"]["kernel"], pc["d1"]["kernel"])
        np.testing.assert_array_equal(pa["d2"]["kernel"], pc["d2"]["kernel"])

    def test_mid_epoch_resume_matches_straight_run(self, tmp_path):
        est_a = _estimator()
        ra = est_a.train(_fs(), batch_size=8, end_trigger=MaxEpoch(3))

        # stop mid-epoch-2 (iteration 6 of 12), snapshotting there
        ck = str(tmp_path / "ck")
        est_b = _estimator()
        est_b.set_checkpoint(ck)
        est_b.train(_fs(), batch_size=8, end_trigger=MaxIteration(6),
                    checkpoint_trigger=SeveralIteration(6))
        est_c = _estimator()
        est_c.load_checkpoint(os.path.join(ck, "snapshot-6"))
        assert est_c.global_step == 6
        rc = est_c.train(_fs(), batch_size=8, end_trigger=MaxEpoch(3))

        np.testing.assert_allclose(ra["loss_history"][6:],
                                   rc["loss_history"], rtol=0, atol=0)
        np.testing.assert_array_equal(est_a.get_params()["d2"]["kernel"],
                                      est_c.get_params()["d2"]["kernel"])

    def test_corrupt_checkpoint_rejected(self, tmp_path):
        import orbax.checkpoint as ocp
        bad = str(tmp_path / "bad")
        ocp.PyTreeCheckpointer().save(bad, {"params": {"d1": np.zeros(3)}})
        est = _estimator()
        with pytest.raises(ValueError, match="not an estimator snapshot"):
            est.load_checkpoint(bad)

    def test_structure_mismatch_rejected(self, tmp_path):
        ck = str(tmp_path / "ck")
        est_a = _estimator()
        est_a.set_checkpoint(ck)
        est_a.train(_fs(), batch_size=8, epochs=1)
        # a DIFFERENT architecture must refuse the snapshot once initialized
        other = Sequential([Dense(4, name="other1"), Dense(2, name="other2")])
        est_b = Estimator(
            model=other,
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.SGD(0.1))
        x, y = _data()
        est_b.train(FeatureSet.from_ndarrays(x, y), batch_size=8, epochs=1)
        with pytest.raises(ValueError, match="structure does not match"):
            est_b.load_checkpoint(est_a._latest_snapshot())
