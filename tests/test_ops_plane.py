"""Ops-plane unit tests: the structured event log (fork-safe, typed,
crash-tolerant), the metric history sampler (counter-delta semantics on a
fake clock), multi-window burn-rate and sustained-threshold SLO rules,
alert-engine hysteresis, incident-bundle causal ordering, the timeline
CLI, and the histogram re-registration pinning test."""
import json
import os

import pytest

from analytics_zoo_tpu.common import faults, metrics
from analytics_zoo_tpu.common.config import global_config
from analytics_zoo_tpu.ops import alerts, events, incident
from analytics_zoo_tpu.ops.__main__ import main as ops_cli
from analytics_zoo_tpu.ops.history import MetricHistory

T0 = 1_000_000.0  # fake wall-clock epoch for the burn-rate math


@pytest.fixture
def reg():
    r = metrics.Registry(capacity=8192)
    yield r
    r.close()


@pytest.fixture
def elog(tmp_path):
    log = events.EventLog(root=str(tmp_path / "spool"), enabled=True)
    yield log
    log.close()


# -- event log ----------------------------------------------------------------

_E_ALPHA = events.event_type("test.alpha", "ops-plane test event")
_E_BETA = events.event_type("test.beta", "ops-plane test event")


class TestEventLog:
    def test_disabled_emit_is_noop_and_creates_nothing(self, tmp_path):
        root = tmp_path / "never"
        log = events.EventLog(root=str(root), enabled=False)
        assert log.emit("test.alpha", n=1) is None
        assert not root.exists()  # disabled plane must not touch disk

    def test_unregistered_type_raises(self, elog):
        with pytest.raises(ValueError, match="never registered"):
            elog.emit("test.totally_unknown")

    def test_reserved_field_collision_raises(self, elog):
        with pytest.raises(ValueError, match="reserved"):
            elog.emit("test.alpha", wall=123.0)
        with pytest.raises(ValueError, match="reserved"):
            elog.emit("test.alpha", pid=1)

    def test_emit_stamps_and_ring_bound(self, tmp_path):
        log = events.EventLog(root=str(tmp_path / "s"), ring=4,
                              enabled=True)
        for i in range(10):
            ev = log.emit("test.alpha", label="lab", n=i)
            assert ev["type"] == "test.alpha"
            assert ev["pid"] == os.getpid()
            assert ev["label"] == "lab"
            assert ev["wall"] > 0 and ev["mono"] > 0
        tail = log.tail()
        assert [e["n"] for e in tail] == [6, 7, 8, 9]  # ring kept newest 4
        assert [e["seq"] for e in tail] == [7, 8, 9, 10]
        # the part file kept everything the ring dropped
        assert len(log.read()) == 10
        log.close()

    def test_read_filters(self, elog):
        elog.emit("test.alpha", label="a")
        elog.emit("test.beta", label="b")
        mid = elog.read()[-1]["wall"]
        elog.emit("test.alpha", label="b")
        assert [e["type"] for e in elog.read(types=["test.beta"])] \
            == ["test.beta"]
        assert len(elog.read(label="b")) == 2
        assert all(e["wall"] >= mid for e in elog.read(since_wall=mid))

    def test_torn_and_garbage_lines_skipped(self, elog):
        elog.emit("test.alpha", n=1)
        part = os.path.join(elog.root, f"{os.getpid()}.jsonl")
        with open(part, "a") as f:
            f.write("42\n")                       # non-dict JSON
            f.write('{"no_type": true}\n')        # dict without type
            f.write('{"type": "test.alpha", "wall": ')  # torn final line
        evs = elog.read()
        assert len(evs) == 1 and evs[0]["n"] == 1

    def test_clear_drops_ring_and_parts(self, elog):
        elog.emit("test.alpha")
        elog.clear()
        assert elog.tail() == [] and elog.read() == []


def test_fork_child_events_visible_to_parent(tmp_path):
    """A forked child's transitions land in the parent's merged view —
    the per-pid part-file handle is re-resolved after the fork."""
    log = events.EventLog(root=str(tmp_path / "spool"), enabled=True)
    log.emit("test.alpha", label="parent")  # opens the parent's part file
    pid = os.fork()
    if pid == 0:
        code = 1
        try:
            log.emit("test.alpha", label="child")
            code = 0
        finally:
            os._exit(code)
    _, status = os.waitpid(pid, 0)
    assert os.waitstatus_to_exitcode(status) == 0
    evs = log.read(types=["test.alpha"])
    assert {e["label"] for e in evs} == {"parent", "child"}
    assert len({e["pid"] for e in evs}) == 2
    # the child must not have deleted the shared spool on exit
    assert os.path.isdir(log.root)
    log.close()


# -- metric history -----------------------------------------------------------

class TestMetricHistory:
    def test_samples_all_registry_shapes(self, reg):
        reg.counter("t.reqs_total").inc(3)
        reg.gauge("t.depth").set(7.0)
        reg.counter("t.labeled_total", labels=("k",)).labels(k="a").inc(2)
        h = reg.histogram("t.lat_seconds")
        h.observe(0.1)
        hist = MetricHistory(reg, depth=16)
        hist.sample_once(now=T0)
        assert hist.latest("t.reqs_total") == (T0, 3.0)
        assert hist.latest("t.depth") == (T0, 7.0)
        assert hist.latest("t.labeled_total", "k=a") == (T0, 2.0)
        summ = hist.latest("t.lat_seconds")[1]
        assert summ["count"] == 1
        assert hist.kind("t.reqs_total") == "counter"
        assert hist.labels_for("t.labeled_total") == ["k=a"]

    def test_counter_delta_is_reset_tolerant(self, reg):
        """PromQL-increase semantics: positive increments summed, a
        decrease (restart / zero_all) contributes the post-reset value —
        this is the sampler half of the satellite-f regression pair."""
        c = reg.counter("t.work_total")
        hist = MetricHistory(reg, depth=64)
        hist.sample_once(now=T0)          # 0
        c.inc(10)
        hist.sample_once(now=T0 + 1)      # 10
        c.inc(15)
        hist.sample_once(now=T0 + 2)      # 25
        hist.sample_once(now=T0 + 3)      # 25 (flat)
        reg.zero()                        # the restart / bench-leg reset
        c.inc(5)
        hist.sample_once(now=T0 + 4)      # 5  (decrease vs 25)
        c.inc(3)
        hist.sample_once(now=T0 + 5)      # 8
        # +10 +15 +0, reset contributes post-reset 5, then +3  == 33
        assert hist.delta("t.work_total", now=T0 + 5) == pytest.approx(33.0)

    def test_delta_prewindow_baseline_and_empty_window(self, reg):
        c = reg.counter("t.base_total")
        hist = MetricHistory(reg, depth=64)
        c.inc(100)
        hist.sample_once(now=T0)          # pre-window baseline sample
        c.inc(7)
        hist.sample_once(now=T0 + 20)     # only in-window sample
        # window [T0+10, T0+30]: baseline 100 seeds, first increment kept
        assert hist.delta("t.base_total", seconds=20, now=T0 + 30) \
            == pytest.approx(7.0)
        # a window with no samples at all is None, not 0.0
        assert hist.delta("t.base_total", seconds=5, now=T0 + 100) is None
        assert hist.delta("t.missing_total") is None

    def test_rate_window_and_dump(self, reg):
        c = reg.counter("t.rate_total")
        hist = MetricHistory(reg, depth=64)
        for s in range(11):
            c.inc(2)
            hist.sample_once(now=T0 + s)
        assert hist.rate("t.rate_total", seconds=10.0, now=T0 + 10) \
            == pytest.approx(2.0)
        win = hist.window("t.rate_total", seconds=3.0, now=T0 + 10)
        assert [t for t, _ in win] == [T0 + 7, T0 + 8, T0 + 9, T0 + 10]
        dump = hist.dump(seconds=3.0, now=T0 + 10)
        assert dump["t.rate_total"][""] == [[t, v] for t, v in win]

    def test_histogram_key_extraction(self, reg):
        h = reg.histogram("t.wait_seconds")
        hist = MetricHistory(reg, depth=16)
        for v in (0.1, 0.1, 0.1):
            h.observe(v)
        hist.sample_once(now=T0)
        for v in (0.1, 0.1):
            h.observe(v)
        hist.sample_once(now=T0 + 1)
        # delta on key="count" gives windowed event counts for ratio rules
        assert hist.delta("t.wait_seconds", seconds=10, now=T0 + 1,
                          key="count") == pytest.approx(2.0)
        assert hist.latest("t.wait_seconds")[1]["p50"] > 0


# -- burn-rate rules on a fake clock ------------------------------------------

class TestBurnRateRule:
    def _drive(self, reg, hist, seconds, bad_per_s, tot_per_s, start=0):
        bad = reg.counter("slo.bad_total")
        tot = reg.counter("slo.req_total")
        for s in range(start, start + seconds):
            if bad_per_s:
                bad.inc(bad_per_s)
            if tot_per_s:
                tot.inc(tot_per_s)
            hist.sample_once(now=T0 + s)
        return T0 + start + seconds - 1

    def test_fast_burn_fires_and_short_window_clears_it(self, reg):
        hist = MetricHistory(reg, depth=256)
        rule = alerts.BurnRateRule(
            "burn", bad="slo.bad_total", total="slo.req_total",
            objective=0.99, windows=((30.0, 5.0, 14.4),))
        # 50% failure ratio -> burn 50x against a 1% budget: fires
        now = self._drive(reg, hist, 20, bad_per_s=5, tot_per_s=10)
        firing, info = rule.evaluate(hist, now)
        assert firing
        assert info["factor"] == 14.4
        assert info["burn_long"] > 14.4 and info["burn_short"] > 14.4
        # bleeding stops; the 5 s short window drains long before the
        # 30 s long window forgets -> the AND clears fast
        now = self._drive(reg, hist, 26, bad_per_s=0, tot_per_s=10,
                          start=20)
        firing, _ = rule.evaluate(hist, now)
        assert not firing

    def test_slow_burn_pair_catches_moderate_burn(self, reg):
        hist = MetricHistory(reg, depth=256)
        rule = alerts.BurnRateRule(
            "burn", bad="slo.bad_total", total="slo.req_total",
            objective=0.99,
            windows=((30.0, 5.0, 14.4), (60.0, 10.0, 6.0)))
        # 10% ratio -> burn ~10x: below the fast factor, above the slow
        now = self._drive(reg, hist, 20, bad_per_s=1, tot_per_s=10)
        firing, info = rule.evaluate(hist, now)
        assert firing
        assert info["factor"] == 6.0

    def test_exact_boundary_never_flaps(self, reg):
        """Strict >: a burn sitting exactly ON the factor holds steady.
        objective 0.75 makes the budget (0.25) and the burn (2.0) exact
        in binary, so this really exercises the boundary."""
        hist = MetricHistory(reg, depth=256)
        rule = alerts.BurnRateRule(
            "burn", bad="slo.bad_total", total="slo.req_total",
            objective=0.75, windows=((30.0, 5.0, 2.0),))
        now = self._drive(reg, hist, 20, bad_per_s=1, tot_per_s=2)
        assert rule.burn_rate(hist, 30.0, now) == 2.0  # exactly the factor
        assert not rule.evaluate(hist, now)[0]
        # one extra bad event pushes it strictly past -> fires
        reg.counter("slo.bad_total").inc(3)
        reg.counter("slo.req_total").inc(2)
        hist.sample_once(now=now + 1)
        assert rule.evaluate(hist, now + 1)[0]

    def test_silence_is_not_a_violation(self, reg):
        hist = MetricHistory(reg, depth=16)
        rule = alerts.BurnRateRule(
            "burn", bad="slo.bad_total", total="slo.req_total",
            objective=0.99, windows=((30.0, 5.0, 1.0),), min_total=5.0)
        assert rule.burn_rate(hist, 30.0, T0) is None  # no samples
        assert not rule.evaluate(hist, T0)[0]
        # traffic below min_total still refuses to judge
        reg.counter("slo.bad_total").inc(1)
        reg.counter("slo.req_total").inc(1)
        hist.sample_once(now=T0)
        hist.sample_once(now=T0 + 1)
        assert rule.burn_rate(hist, 30.0, T0 + 1) is None


class TestThresholdRule:
    def test_sustained_for_s(self, reg):
        lag = reg.gauge("t.lag_depth")
        hist = MetricHistory(reg, depth=64)
        rule = alerts.ThresholdRule("lag_high", "t.lag_depth",
                                    above=2.0, for_s=10.0)
        lag.set(5.0)
        for s in range(5):
            hist.sample_once(now=T0 + s)
        # breaching, but history does not reach back for_s yet
        assert not rule.evaluate(hist, T0 + 4)[0]
        for s in range(5, 21):
            hist.sample_once(now=T0 + s)
        firing, info = rule.evaluate(hist, T0 + 20)
        assert firing and info["value"] == 5.0
        # one calm sample inside the window breaks "sustained"
        lag.set(1.0)
        hist.sample_once(now=T0 + 21)
        lag.set(5.0)
        hist.sample_once(now=T0 + 22)
        assert not rule.evaluate(hist, T0 + 22)[0]


# -- alert engine -------------------------------------------------------------

class TestAlertEngine:
    def test_hysteresis_and_alert_events(self, reg, elog):
        lag = reg.gauge("t.engine_depth")
        hist = MetricHistory(reg, depth=64)
        rule = alerts.ThresholdRule("depth_high", "t.engine_depth",
                                    above=2.0, clear_holds=2)
        fired = []
        eng = alerts.AlertEngine(
            hist, [rule], log=elog, interval_s=999.0,
            on_fire=lambda name, info, t: fired.append((name, t)))
        lag.set(9.0)
        hist.sample_once(now=T0)
        trans = eng.evaluate(now=T0)
        assert [(t["name"], t["state"]) for t in trans] \
            == [("depth_high", "fire")]
        assert fired == [("depth_high", T0)]
        assert "depth_high" in eng.active_alerts()
        # still firing: no new transition, info refreshed in place
        hist.sample_once(now=T0 + 1)
        assert eng.evaluate(now=T0 + 1) == []
        # calm pass #1: held active (clear_holds=2)
        lag.set(0.0)
        hist.sample_once(now=T0 + 2)
        assert eng.evaluate(now=T0 + 2) == []
        assert "depth_high" in eng.active_alerts()
        # calm pass #2: clears
        hist.sample_once(now=T0 + 3)
        trans = eng.evaluate(now=T0 + 3)
        assert [(t["name"], t["state"]) for t in trans] \
            == [("depth_high", "clear")]
        assert eng.active_alerts() == {}
        # both transitions are themselves events on the timeline
        states = [e["state"] for e in elog.read(types=["ops.alert"])]
        assert states == ["fire", "clear"]

    def test_on_fire_seals_incident_with_alert_attached(self, reg, elog,
                                                        tmp_path):
        lag = reg.gauge("t.seal_depth")
        hist = MetricHistory(reg, depth=64)
        rule = alerts.ThresholdRule("seal_high", "t.seal_depth", above=1.0)
        corr = incident.IncidentCorrelator(
            log=elog, history=hist, out_dir=str(tmp_path / "inc"),
            window_s=10 * 24 * 3600.0)
        sealed = []
        eng = alerts.AlertEngine(
            hist, [rule], log=elog, interval_s=999.0,
            on_fire=lambda name, info, t: sealed.append(corr.seal(
                reason=f"alert:{name}",
                alert={"name": name, "info": info, "wall": t}, now=t)))
        elog.emit("test.alpha", label="ctx")  # context before the alert
        lag.set(5.0)
        hist.sample_once(now=T0)
        eng.evaluate(now=T0)
        assert len(sealed) == 1
        bundle = incident.load_bundle(sealed[0])
        assert bundle["reason"] == "alert:seal_high"
        assert bundle["alert"]["name"] == "seal_high"
        types = [e["type"] for e in bundle["events"]]
        # the window holds both the context event and the firing alert
        assert "test.alpha" in types and "ops.alert" in types
        assert types.index("test.alpha") < types.index("ops.alert")
        assert "t.seal_depth" in bundle["history"]
        with open(os.path.join(sealed[0], "timeline.txt")) as f:
            tl = f.read()
        assert "triggering alert: seal_high" in tl


def test_ensure_default_gated_on_ops_enabled(tmp_path):
    assert alerts.ensure_default() is None  # ops.enabled defaults off
    global_config().set("ops.enabled", True)
    events.reset_default(root=str(tmp_path / "spool"), enabled=True)
    try:
        eng = alerts.ensure_default()
        assert eng is not None
        assert alerts.ensure_default() is eng  # idempotent
        assert alerts.active_alerts() == {}
    finally:
        alerts.shutdown_default()
        events.reset_default(enabled=False)
        global_config().unset("ops.enabled")
    assert alerts.active_alerts() == {}


# -- incident ordering and bundles --------------------------------------------

class TestCausalOrder:
    def test_mono_within_pid_wall_bracketed_across(self):
        evs = [
            {"type": "serving.brownout_rung", "wall": 10.00, "mono": 5.0,
             "seq": 1, "pid": 1, "label": "a"},
            {"type": "fleet.breaker", "wall": 10.05, "mono": 900.0,
             "seq": 1, "pid": 2, "label": "c"},
            {"type": "serving.brownout_rung", "wall": 10.20, "mono": 5.5,
             "seq": 2, "pid": 1, "label": "a"},
            {"type": "fleet.scale", "wall": 10.10, "mono": 901.0,
             "seq": 2, "pid": 2, "label": "d"},
        ]
        ordered = incident.order_events(reversed(evs))
        assert [(e["pid"], e["seq"]) for e in ordered] \
            == [(1, 1), (2, 1), (2, 2), (1, 2)]

    def test_ntp_step_cannot_reorder_one_pid(self):
        # wall steps BACKWARD mid-incident; mono order must win in-pid
        evs = [
            {"type": "test.alpha", "wall": 100.0, "mono": 1.0, "seq": 1,
             "pid": 7},
            {"type": "test.beta", "wall": 40.0, "mono": 2.0, "seq": 2,
             "pid": 7},
        ]
        ordered = incident.order_events(evs)
        assert [e["type"] for e in ordered] == ["test.alpha", "test.beta"]

    def test_render_timeline_offsets_and_fields(self):
        evs = incident.order_events([
            {"type": "test.alpha", "wall": 100.0, "mono": 1.0, "seq": 1,
             "pid": 7, "label": "srv", "n": 3},
            {"type": "test.beta", "wall": 101.5, "mono": 2.0, "seq": 2,
             "pid": 7, "label": "", "detail": {"b": 1, "a": 2}},
        ])
        tl = incident.render_timeline(evs, reason="manual")
        lines = tl.splitlines()
        assert lines[0] == "incident: manual"
        assert "t0 = 100.000" in lines[1]
        assert "[7/srv]" in lines[2] and "n=3" in lines[2]
        assert "+   1.500s" in lines[3] and '{"a": 2, "b": 1}' in lines[3]
        assert incident.render_timeline([]).rstrip() \
            == "(no events in window)"


class TestIncidentBundle:
    def test_scripted_brownout_breaker_scale_golden_order(self, elog, reg,
                                                          tmp_path):
        """The acceptance-shaped sequence: rung climb, breaker trip,
        scale-out must come back from a sealed bundle in exactly that
        causal order."""
        events.event_type("serving.brownout_rung", "")
        events.event_type("fleet.breaker", "")
        events.event_type("fleet.scale", "")
        elog.emit("serving.brownout_rung", label="a", level_from=0,
                  level_to=2, pressure=0.91)
        elog.emit("fleet.breaker", label="c", state="open",
                  state_from="closed", reason="latency")
        elog.emit("fleet.scale", label="fleet", direction="out")

        health_ok = tmp_path / "a.health.json"
        health_ok.write_text(json.dumps({"state": "running", "depth": 3}))
        health_bad = tmp_path / "b.health.json"
        health_bad.write_text("{torn")

        hist = MetricHistory(reg, depth=16)
        reg.counter("t.ctx_total").inc(4)
        hist.sample_once()
        corr = incident.IncidentCorrelator(
            log=elog, history=hist, out_dir=str(tmp_path / "inc"),
            window_s=3600.0,
            health_paths=[str(health_ok), str(health_bad)])
        bdir = corr.seal(reason="chaos-capstone")

        bundle = incident.load_bundle(bdir)
        types = [e["type"] for e in bundle["events"]]
        assert types == ["serving.brownout_rung", "fleet.breaker",
                         "fleet.scale"]
        assert bundle["health"][str(health_ok)]["state"] == "running"
        assert bundle["health"][str(health_bad)] is None  # frozen evidence
        assert bundle["history"]["t.ctx_total"][""][0][1] == 4.0

        with open(os.path.join(bdir, "timeline.txt")) as f:
            tl = f.read()
        assert tl.index("serving.brownout_rung") \
            < tl.index("fleet.breaker") < tl.index("fleet.scale")
        assert "level_to=2" in tl and "reason=latency" in tl

        last = incident.last_incident()
        assert last["path"] == bdir and last["reason"] == "chaos-capstone"
        # sealing is itself an event a LATER timeline will show
        assert [e["reason"] for e in elog.read(types=["ops.incident"])] \
            == ["chaos-capstone"]

    def test_cli_timeline_seal_show(self, elog, tmp_path, capsys):
        elog.emit("test.alpha", label="x", n=1)
        elog.emit("test.beta", label="y")
        spool = elog.root
        parts_before = sorted(os.listdir(spool))

        assert ops_cli(["timeline", "--events", spool]) == 0
        out = capsys.readouterr().out
        assert "test.alpha" in out and "test.beta" in out
        assert out.index("test.alpha") < out.index("test.beta")

        out_dir = str(tmp_path / "cli_inc")
        assert ops_cli(["seal", "--events", spool, "--out", out_dir,
                        "--reason", "manual-probe",
                        "--window-s", "3600"]) == 0
        bdir = capsys.readouterr().out.strip()
        assert os.path.isfile(os.path.join(bdir, "bundle.json"))
        # the forensic reader never writes the spool it reads
        assert sorted(os.listdir(spool)) == parts_before

        assert ops_cli(["show", bdir]) == 0
        assert "manual-probe" in capsys.readouterr().out
        assert ops_cli(["show", bdir, "--json"]) == 0
        assert json.loads(capsys.readouterr().out)["reason"] \
            == "manual-probe"


# -- retrofitted emitters -----------------------------------------------------

def test_fault_fire_emits_event(tmp_path):
    """`fault.fired` registers lazily and lands on the timeline when a
    chaos site fires."""
    faults.reset()
    log = events.reset_default(root=str(tmp_path / "spool"), enabled=True)
    try:
        global_config().set("faults.plan", "train.step:1")
        with pytest.raises(faults.FaultInjected):
            faults.inject("train.step")
        evs = log.read(types=["fault.fired"])
        assert len(evs) == 1 and evs[0]["site"] == "train.step"
    finally:
        faults.reset()
        global_config().unset("faults.plan")
        events.reset_default(enabled=False)


@pytest.mark.pod(budget_s=2.0)  # spawns nothing; marker satisfies the
def test_supervisor_status_carries_alert_state():  # source-scan lint
    from analytics_zoo_tpu.cluster.supervisor import FleetSupervisor
    sup = FleetSupervisor.__new__(FleetSupervisor)
    sup._procs, sup._draining = {}, set()
    st = sup.status()
    assert st["alerts"] == [] and st["instances"] == []
    assert st["incident"] is None or isinstance(st["incident"], dict)


# -- satellite f: histogram re-registration pinning ---------------------------

class TestHistogramReRegistration:
    def test_percentile_stable_across_idempotent_reregistration(self, reg):
        """Fork-inherited slab pattern: a child (or a late importer)
        re-registers the same histogram family idempotently. Percentile
        and count must reflect ALL observations regardless of which
        handle made or reads them — pinned here so a stale-handle
        regression cannot land silently."""
        h1 = reg.histogram("t.pin_seconds")
        for v in (0.01,) * 20 + (0.5,) * 20:
            h1.observe(v)
        p50_before = h1.percentile(0.5)
        count_before = h1.count()

        h2 = reg.histogram("t.pin_seconds")  # idempotent re-registration
        assert h2.count() == count_before
        assert h2.percentile(0.5) == p50_before
        assert h2.percentile(0.99) == h1.percentile(0.99)

        # new observations through EITHER handle visible through both
        h2.observe(10.0)
        assert h1.count() == count_before + 1
        assert h1.percentile(1.0) == h2.percentile(1.0)

        # and the history sampler sees one merged series, not two
        hist = MetricHistory(reg, depth=8)
        hist.sample_once(now=T0)
        assert hist.latest("t.pin_seconds")[1]["count"] == count_before + 1
