"""Distributed XShard ETL engine: shared-memory shuffle, disk spill, and
the zero-copy handoff into training.

The contract under test everywhere: every distributed op (map / filter /
groupby-agg / join) is BIT-IDENTICAL to the single-process pandas
reference — not merely allclose — because the combine stage runs pandas'
own kernels per destination partition; ``to_featureset`` lowers without a
single full-dataset host copy (training batches read from the very slab
bytes the ETL workers wrote); blocks over the slab budget spill to memmap
files with identical results; and the worker fleet self-heals through
SIGKILLs and transient task faults with exact results.
"""
import multiprocessing
import os

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common.config import global_config
from analytics_zoo_tpu.xshard import (DataShards, EtlEngine, XShard,
                                      XShardWorkerError, read_csv)
from analytics_zoo_tpu.xshard import engine as _eng
from analytics_zoo_tpu.zouwu import (lag_feature_cols, roll_windows,
                                     rolled_featureset)


def make_df(n=200, seed=0, nkeys=17):
    rs = np.random.RandomState(seed)
    return pd.DataFrame({
        "k": rs.randint(0, nkeys, n).astype(np.int64),
        "g": rs.randint(0, 5, n).astype(np.int32),
        "x": rs.rand(n).astype(np.float64),
        "y": rs.rand(n).astype(np.float32),
    })


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    yield
    faults.reset()
    cfg = global_config()
    for key in ("data.handoff", "data.task_retries", "data.worker_respawns",
                "xshard.num_workers", "xshard.partitions", "xshard.slab_mb",
                "xshard.spill_dir"):
        cfg.unset(key)


@pytest.fixture()
def eng():
    e = EtlEngine(num_workers=2)
    yield e
    e.close()


def exact_frames(got, want):
    """Bit-exact frame comparison: same columns, dtypes, and VALUES —
    float columns compared with ``==``, not a tolerance."""
    assert list(got.columns) == list(want.columns)
    assert len(got) == len(want)
    for c in want.columns:
        a, b = got[c].to_numpy(), want[c].to_numpy()
        assert a.dtype == b.dtype, c
        assert (a == b).all(), c


class TestShuffleParity:
    """map / filter / groupby / join vs single-process pandas, bitwise."""

    def test_map_parity(self, ctx, eng):
        df = make_df()
        fn = lambda d: d.assign(z=d.x * 2.0 + d.y)  # noqa: E731
        xs = XShard.from_pandas(df, 4, engine=eng)
        got = xs.map(fn).to_pandas()
        exact_frames(got, fn(df))

    def test_filter_parity(self, ctx, eng):
        df = make_df()
        xs = XShard.from_pandas(df, 4, engine=eng)
        got = xs.filter(lambda d: d.x > 0.5).to_pandas()
        exact_frames(got, df[df.x > 0.5].reset_index(drop=True))

    def test_groupby_sum_mean_bitwise(self, ctx, eng):
        # float group sums in pandas >= 1.3 are Kahan-compensated; the
        # engine must reproduce them BITWISE, which only holds because
        # the combine stage runs pandas' own groupby per destination
        df = make_df(n=500)
        xs = XShard.from_pandas(df, 4, engine=eng)
        got = (xs.groupby("k").agg({"x": "sum", "y": "mean"}).to_pandas()
               .sort_values("k").reset_index(drop=True))
        want = df.groupby("k", as_index=False).agg({"x": "sum", "y": "mean"})
        exact_frames(got, want)

    def test_groupby_multikey_min_max_count(self, ctx, eng):
        df = make_df(n=400)
        xs = XShard.from_pandas(df, 3, engine=eng)
        got = (xs.groupby(["k", "g"])
               .agg({"x": "min", "y": "max"}).to_pandas()
               .sort_values(["k", "g"]).reset_index(drop=True))
        want = df.groupby(["k", "g"], as_index=False).agg(
            {"x": "min", "y": "max"})
        exact_frames(got, want)

    def test_join_parity(self, ctx, eng):
        rs = np.random.RandomState(3)
        left = pd.DataFrame({"k": rs.randint(0, 12, 150).astype(np.int64),
                             "i": np.arange(150, dtype=np.int64),
                             "x": rs.rand(150)})
        right = pd.DataFrame({"k": rs.randint(0, 12, 60).astype(np.int64),
                              "j": np.arange(60, dtype=np.int64),
                              "w": rs.rand(60).astype(np.float32)})
        xl = XShard.from_pandas(left, 4, engine=eng)
        xr = XShard.from_pandas(right, 3, engine=eng)
        got = (xl.join(xr, on="k").to_pandas()
               .sort_values(["i", "j"]).reset_index(drop=True))
        want = (left.merge(right, on="k", how="inner")
                .sort_values(["i", "j"]).reset_index(drop=True))
        exact_frames(got, want)

    def test_join_guards(self, ctx, eng):
        df = make_df(n=20)
        xa = XShard.from_pandas(df, 2, engine=eng)
        xb = XShard.from_pandas(df, 2, engine=eng)
        with pytest.raises(ValueError, match="inner"):
            xa.join(xb, on="k", how="left")
        with pytest.raises(ValueError, match="overlap"):
            xa.join(xb, on="k")  # g/x/y collide

    def test_chained_pipeline_parity(self, ctx, eng):
        df = make_df(n=300, seed=9)
        xs = XShard.from_pandas(df, 4, engine=eng)
        got = (xs.map(lambda d: d.assign(x2=d.x * d.x))
               .filter(lambda d: d.g != 2)
               .groupby("k").agg({"x2": "sum"}).to_pandas()
               .sort_values("k").reset_index(drop=True))
        ref = df.assign(x2=df.x * df.x)
        ref = ref[ref.g != 2]
        want = ref.groupby("k", as_index=False).agg({"x2": "sum"})
        exact_frames(got, want)

    def test_introspection_and_partition_convention(self, ctx, eng):
        df = make_df(n=10)
        xs = XShard.from_pandas(df, 3, engine=eng)
        assert xs.num_partitions() == 3
        assert xs.count() == 10
        assert xs.columns == ["k", "g", "x", "y"]
        # np.array_split size convention: 4, 3, 3
        assert [r.rows for r in xs._refs] == [4, 3, 3]
        parts = xs.collect()
        exact_frames(pd.concat(parts, ignore_index=True), df)

    def test_distributed_read_files(self, ctx, eng, tmp_path):
        dfs = [make_df(n=30, seed=s) for s in range(3)]
        for i, d in enumerate(dfs):
            d.to_csv(tmp_path / f"part{i}.csv", index=False)
        paths = sorted(str(p) for p in tmp_path.glob("*.csv"))
        xs = XShard.read_files(paths, "csv", engine=eng)
        assert xs.num_partitions() == 3
        got = xs.to_pandas()
        # the reference is what pandas itself reads back (csv round-trips
        # widen int32/float32), loaded the single-process way
        want = pd.concat([pd.read_csv(p) for p in paths],
                         ignore_index=True)
        pd.testing.assert_frame_equal(got, want, check_exact=True)


class TestSpill:
    """Partitions over the slab budget go through the memmap spill path
    with identical results."""

    def test_spill_bit_parity_and_cleanup(self, ctx):
        e = EtlEngine(num_workers=2, slab_bytes=1024)  # everything spills
        spill_dir = e.spill_dir
        before = _eng._M_SPILL.value()
        try:
            df = make_df(n=2000)
            xs = XShard.from_pandas(df, 4, engine=e)
            assert all(r.kind == "mmap" for r in xs._refs)
            got = (xs.groupby("k").agg({"x": "sum"}).to_pandas()
                   .sort_values("k").reset_index(drop=True))
            exact_frames(got, df.groupby("k", as_index=False)
                         .agg({"x": "sum"}))
            assert _eng._M_SPILL.value() > before
            assert any(f.endswith(".mmap") for f in os.listdir(spill_dir))
        finally:
            e.close()
        assert not os.path.exists(spill_dir)  # own temp dir removed

    def test_spilled_handoff_matches_slab_handoff(self, ctx):
        df = make_df(n=600)
        small = EtlEngine(num_workers=2, slab_bytes=512)
        big = EtlEngine(num_workers=2)
        try:
            fa = XShard.from_pandas(df, 3, engine=small).to_featureset(
                ["x", "y"], "g")
            fb = XShard.from_pandas(df, 3, engine=big).to_featureset(
                ["x", "y"], "g")
            np.testing.assert_array_equal(np.asarray(fa.features),
                                          np.asarray(fb.features))
            np.testing.assert_array_equal(np.asarray(fa.labels),
                                          np.asarray(fb.labels))
        finally:
            small.close()
            big.close()


class TestZeroCopyHandoff:
    """to_featureset writes partition rows straight into ONE shared
    segment the FeatureSet wraps — no driver concat, no second copy."""

    def test_matches_from_dataframe_exactly(self, ctx, eng):
        from analytics_zoo_tpu.feature.featureset import FeatureSet
        df = make_df(n=257)  # odd size: uneven partition tails
        fs = XShard.from_pandas(df, 4, engine=eng).to_featureset(
            ["x", "y"], "g")
        ref = FeatureSet.from_dataframe(df, ["x", "y"], ["g"], stack=True)
        got_x, want_x = np.asarray(fs.features), np.asarray(ref.features)
        assert got_x.dtype == want_x.dtype == np.float32
        np.testing.assert_array_equal(got_x, want_x)
        got_y, want_y = np.asarray(fs.labels), np.asarray(ref.labels)
        assert got_y.dtype == want_y.dtype  # label dtype preserved
        np.testing.assert_array_equal(got_y, want_y)

    def test_no_driver_concat_or_dataframe_rebuild(self, ctx, eng,
                                                   monkeypatch):
        """The slab path must never route through pd.concat or
        from_dataframe in the DRIVER (workers are already forked, so
        their legitimate pandas use is untouched)."""
        from analytics_zoo_tpu.feature import featureset as fsmod
        df = make_df(n=100)
        xs = XShard.from_pandas(df, 3, engine=eng)

        def boom(*a, **k):
            raise AssertionError("full-dataset gather in the driver")

        monkeypatch.setattr(pd, "concat", boom)
        monkeypatch.setattr(fsmod.FeatureSet, "from_dataframe",
                            classmethod(boom))
        fs = xs.to_featureset(["x", "y"], "g")
        assert np.asarray(fs.features).shape == (100, 2)

    def test_batches_read_worker_written_slab_bytes(self, ctx, eng):
        """Memory-sharing proof: the FeatureSet's arrays ARE views into
        the handoff segment, and a batch drawn after mutating the segment
        observes the mutation — training reads the ETL workers' bytes."""
        df = make_df(n=64)
        fs = XShard.from_pandas(df, 2, engine=eng).to_featureset(
            ["x", "y"], "g")
        shm = fs._shm_keepalive._shms[0]
        feats = fs.features
        assert np.shares_memory(
            feats, np.frombuffer(shm.buf, dtype=np.uint8))
        first = np.asarray(next(iter(fs.eval_iterator(16)))[0]).copy()
        np.testing.assert_array_equal(first[0],
                                      df[["x", "y"]].to_numpy(np.float32)[0])
        feats[0, 0] += 7.0  # scribble on the slab view...
        again = np.asarray(next(iter(fs.eval_iterator(16)))[0])
        assert again[0, 0] == first[0, 0] + np.float32(7.0)  # ...batch sees it

    def test_gather_mode_is_bit_identical_baseline(self, ctx, eng):
        df = make_df(n=120)
        xs = XShard.from_pandas(df, 3, engine=eng)
        slab = xs.to_featureset(["x", "y"], "g")
        global_config().set("data.handoff", "gather")
        eager = xs.to_featureset(["x", "y"], "g")
        np.testing.assert_array_equal(np.asarray(slab.features),
                                      np.asarray(eager.features))
        np.testing.assert_array_equal(np.asarray(slab.labels),
                                      np.asarray(eager.labels))

    def test_feature_shape_is_a_free_view_reshape(self, ctx, eng):
        df = make_df(n=40)
        fs = XShard.from_pandas(df, 2, engine=eng).to_featureset(
            ["x", "y"], "g", feature_shape=(2, 1))
        assert np.asarray(fs.features).shape == (40, 2, 1)

    def test_bad_inputs_raise(self, ctx, eng):
        df = make_df(n=30)
        xs = XShard.from_pandas(df, 2, engine=eng)
        with pytest.raises(KeyError, match="nope"):
            xs.to_featureset(["nope"])
        empty = xs.filter(lambda d: d.x > 2.0)
        with pytest.raises(ValueError, match="empty"):
            empty.to_featureset(["x"])

    def test_trains_through_estimator(self, ctx, eng):
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.keras import (Sequential, objectives,
                                             optimizers)
        from analytics_zoo_tpu.keras.layers import Dense
        df = make_df(n=128, seed=5)
        fs = (XShard.from_pandas(df, 4, engine=eng)
              .map(lambda d: d.assign(z=d.x - d.y))
              .to_featureset(["x", "y", "z"], "g"))
        est = Estimator(
            model=Sequential([Dense(8, activation="relu"), Dense(1)]),
            loss_fn=objectives.get("mse"), optimizer=optimizers.SGD(0.01))
        out = est.train(fs, batch_size=32, epochs=2)
        assert out["iterations"] == 8
        assert np.isfinite(out["loss_history"]).all()


class TestSelfHealing:
    """The ETL fleet survives SIGKILLed workers (respawn + resubmit) and
    transient task faults (``data.task_retries``) with EXACT results."""

    def test_sigkilled_worker_respawns_results_exact(self, ctx):
        df = make_df(n=300)
        want = df.groupby("k", as_index=False).agg({"x": "sum"})
        faults.arm("xshard.kill", at=2, budget=1)  # before the pool forks
        e = EtlEngine(num_workers=2)
        try:
            got = (XShard.from_pandas(df, 4, engine=e)
                   .groupby("k").agg({"x": "sum"}).to_pandas()
                   .sort_values("k").reset_index(drop=True))
        finally:
            e.close()
        assert faults.fire_count("xshard.kill") == 1
        exact_frames(got, want)

    def test_task_retries_absorb_transient_faults(self, ctx):
        global_config().set("data.task_retries", 2)
        faults.arm("xshard.task", at=1, budget=1)
        df = make_df(n=100)
        e = EtlEngine(num_workers=2)
        try:
            got = (XShard.from_pandas(df, 3, engine=e)
                   .map(lambda d: d.assign(z=d.x + 1.0)).to_pandas())
        finally:
            e.close()
        assert faults.fire_count("xshard.task") == 1
        exact_frames(got, df.assign(z=df.x + 1.0))

    def test_retry_budget_exhausts_to_error(self, ctx):
        faults.arm("xshard.task", p=1.0, budget=100)
        e = EtlEngine(num_workers=2)
        try:
            with pytest.raises(XShardWorkerError, match="injected fault"):
                XShard.from_pandas(make_df(n=40), 2, engine=e).map(
                    lambda d: d).collect()
        finally:
            e.close()

    def test_respawn_budget_exhausts_promptly(self, ctx):
        import time
        global_config().set("data.worker_respawns", 0)
        faults.arm("xshard.kill", at=1, budget=1)
        e = EtlEngine(num_workers=2)
        try:
            t0 = time.monotonic()
            with pytest.raises(XShardWorkerError, match="worker died"):
                XShard.from_pandas(make_df(n=40), 2, engine=e).map(
                    lambda d: d).collect()
            assert time.monotonic() - t0 < 10
        finally:
            e.close()

    def test_close_leaves_no_children(self, ctx):
        e = EtlEngine(num_workers=2)
        XShard.from_pandas(make_df(n=40), 2, engine=e).map(
            lambda d: d.assign(z=d.x)).collect()
        e.close()
        ours = [p for p in multiprocessing.active_children()
                if p.name.startswith("zoo-xshard-worker")]
        assert ours == []


class TestDataShardsSatellites:
    """repartition by row-range offsets; parallel multi-file reads; the
    to_xshard bridge."""

    def test_repartition_row_ranges(self, ctx):
        dfs = [make_df(n=n, seed=i) for i, n in enumerate((5, 3, 7))]
        ds = DataShards(dfs)
        want = pd.concat(dfs, ignore_index=True)
        for n in (1, 2, 4, 6):
            rp = ds.repartition(n)
            assert rp.num_partitions() == n
            sizes = [len(s) for s in rp.shards]
            assert sizes == [15 // n + (1 if i < 15 % n else 0)
                             for i in range(n)]
            pd.testing.assert_frame_equal(rp.concat_to_pandas(), want,
                                          check_exact=True)

    def test_repartition_more_parts_than_rows(self, ctx):
        ds = DataShards([make_df(n=2), make_df(n=1, seed=1)])
        rp = ds.repartition(5)
        assert [len(s) for s in rp.shards] == [1, 1, 1, 0, 0]
        assert list(rp.shards[4].columns) == ["k", "g", "x", "y"]
        pd.testing.assert_frame_equal(rp.concat_to_pandas(),
                                      ds.concat_to_pandas(),
                                      check_exact=True)

    def test_read_csv_many_files_in_parallel(self, ctx, tmp_path):
        dfs = [make_df(n=20, seed=s) for s in range(4)]
        for i, d in enumerate(dfs):
            d.to_csv(tmp_path / f"f{i}.csv", index=False)
        ds = read_csv(str(tmp_path))
        assert ds.num_partitions() == 4  # one shard per file, sorted order
        want = pd.concat(
            [pd.read_csv(tmp_path / f"f{i}.csv") for i in range(4)],
            ignore_index=True)  # csv round-trips widen int32/float32
        pd.testing.assert_frame_equal(ds.concat_to_pandas(), want,
                                      check_exact=True)

    def test_to_xshard_bridge(self, ctx, eng):
        dfs = [make_df(n=10, seed=s) for s in range(3)]
        xs = DataShards(dfs).to_xshard(engine=eng)
        assert xs.num_partitions() == 3
        exact_frames(xs.to_pandas(), pd.concat(dfs, ignore_index=True))


class TestZouwuCapstone:
    """Rolling/lag windows computed IN the engine feed a sequence model
    straight from the slabs."""

    def _series(self, n, s0):
        t = np.arange(n, dtype=np.float64)
        return pd.DataFrame({
            "v": np.sin(0.1 * t + s0).astype(np.float64),
            "u": np.cos(0.07 * t + s0).astype(np.float64)})

    def test_roll_windows_per_series_parity(self, ctx, eng):
        s1, s2 = self._series(30, 0.0), self._series(24, 1.0)
        xs = XShard.from_shards([s1, s2], engine=eng)
        rolled, cols = roll_windows(xs, ["v", "u"], lookback=3, horizon=2,
                                    target_col="v")
        assert cols == lag_feature_cols(["v", "u"], 3)
        assert cols[:3] == ["v_lag2", "u_lag2", "v_lag1"]  # time-major
        parts = rolled.collect()
        # windows never cross the series boundary
        assert [len(p) for p in parts] == [30 - 2 - 2, 24 - 2 - 2]
        ref = s1
        want_first = ref.v.to_numpy()[0:3]  # oldest..newest of window 0
        got = parts[0]
        np.testing.assert_array_equal(
            got[["v_lag2", "v_lag1", "v_lag0"]].to_numpy()[0], want_first)
        np.testing.assert_array_equal(got["target"].to_numpy(),
                                      ref.v.to_numpy()[4:])

    def test_rolled_featureset_trains_recurrent_model(self, ctx, eng):
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.keras import (Sequential, objectives,
                                             optimizers)
        from analytics_zoo_tpu.keras.layers import GRU, Dense
        xs = XShard.from_shards(
            [self._series(40, 0.0), self._series(40, 2.0)], engine=eng)
        fs, rolled = rolled_featureset(xs, ["v", "u"], lookback=4,
                                       horizon=1)
        n = rolled.count()
        assert np.asarray(fs.features).shape == (n, 4, 2)
        # sequence features are float32 views over worker-written slabs
        assert np.shares_memory(
            fs.features,
            np.frombuffer(fs._shm_keepalive._shms[0].buf, dtype=np.uint8))
        est = Estimator(model=Sequential([GRU(6), Dense(1)]),
                        loss_fn=objectives.get("mse"),
                        optimizer=optimizers.SGD(0.05))
        out = est.train(fs, batch_size=24, epochs=2)
        assert np.isfinite(out["loss_history"]).all()


@pytest.mark.slow
class TestEtlSweep:
    """Heavy end-to-end sweep: larger tables, every op, spill on and off,
    all bit-identical to pandas."""

    @pytest.mark.parametrize("slab_bytes", [None, 4096])
    def test_full_pipeline_sweep(self, ctx, slab_bytes):
        e = (EtlEngine(num_workers=3) if slab_bytes is None
             else EtlEngine(num_workers=3, slab_bytes=slab_bytes))
        try:
            for n, nkeys, nparts in ((3000, 7, 5), (10000, 257, 8)):
                df = make_df(n=n, seed=n, nkeys=nkeys)
                xs = XShard.from_pandas(df, nparts, engine=e)
                got = (xs.map(lambda d: d.assign(z=d.x * d.y))
                       .filter(lambda d: d.k % 3 != 1)
                       .groupby(["k", "g"])
                       .agg({"z": "sum", "x": "mean", "y": "max"})
                       .to_pandas().sort_values(["k", "g"])
                       .reset_index(drop=True))
                ref = df.assign(z=df.x * df.y)
                ref = ref[ref.k % 3 != 1]
                want = ref.groupby(["k", "g"], as_index=False).agg(
                    {"z": "sum", "x": "mean", "y": "max"})
                exact_frames(got, want)
        finally:
            e.close()
