"""PodLauncher multi-process orchestration: 2 coordinated workers on the CPU
backend drive per-host sharding, global-batch training, rank-0 checkpointing,
and failure detection (reference RayOnSpark launch/guard behavior,
``pyzoo/zoo/ray/raycontext.py:190``)."""
import glob
import json
import os

import numpy as np
import pytest

from analytics_zoo_tpu.cluster import PodLaunchError, PodLauncher


class TestPodTraining:
    @pytest.mark.pod(budget_s=60)
    def test_two_process_train(self, tmp_path):
        workdir = str(tmp_path)
        launcher = PodLauncher(num_processes=2, devices_per_process=2,
                               platform="cpu", log_dir=os.path.join(workdir, "logs"))
        results = launcher.run("tests.pod_workers:train_worker",
                               args=[workdir], timeout=600)
        assert [r.returncode for r in results] == [0, 0]

        reports = {}
        for path in glob.glob(os.path.join(workdir, "done_*.json")):
            with open(path) as f:
                r = json.load(f)
            reports[r["process_index"]] = r
        assert set(reports) == {0, 1}

        # per-host shards are disjoint and cover the dataset
        rows0 = set(reports[0]["shard_rows"])
        rows1 = set(reports[1]["shard_rows"])
        assert rows0.isdisjoint(rows1)
        assert rows0 | rows1 == set(float(i) for i in range(32))

        # synchronous data parallelism: both processes observed the same loss
        assert reports[0]["final_loss"] == pytest.approx(
            reports[1]["final_loss"], abs=1e-6)
        assert reports[0]["iterations"] == reports[1]["iterations"] == 8

        # checkpointing is rank-0-only: exactly one process wrote snapshots
        ckpts = glob.glob(os.path.join(workdir, "ckpt", "*"))
        assert ckpts, "rank 0 wrote no checkpoint"

    @pytest.mark.pod(budget_s=30)
    def test_failure_detection_kills_pod(self, tmp_path):
        """One dead worker must fail the job fast, not hang the collective."""
        launcher = PodLauncher(num_processes=2, devices_per_process=1,
                               platform="cpu",
                               log_dir=os.path.join(str(tmp_path), "logs"))
        with pytest.raises(PodLaunchError) as ei:
            launcher.run("tests.pod_workers:failing_worker",
                         args=[str(tmp_path)], timeout=120)
        # rank 1 raised; rank 0 (blocked in allgather) was terminated
        assert "workers failed" in str(ei.value) or "timed out" in str(ei.value)

    def test_bad_target_rejected(self):
        from analytics_zoo_tpu.cluster.bootstrap import resolve_target
        with pytest.raises(ValueError):
            resolve_target("no_colon_here")


class TestBootstrapGuards:
    @pytest.mark.pod(budget_s=10)
    def test_parent_guard_reaps_orphaned_worker(self, tmp_path):
        """The launcher dying must take its workers with it. Model the
        documented race window — launcher dead before the worker's guard
        even starts — by handing bootstrap a ZOO_TPU_PARENT pid that is
        already gone: the ppid watch fires and the worker exits 113
        instead of serving out its 600s target."""
        import subprocess
        import sys
        launcher = subprocess.Popen([sys.executable, "-c", "pass"])
        launcher.wait()  # "launcher" is dead before the worker starts
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        env.update({
            "ZOO_TPU_PROC_ID": "0", "ZOO_TPU_NPROCS": "1",
            "ZOO_TPU_COORD": "127.0.0.1:1",  # never reached
            "ZOO_TPU_TARGET": "tests.pod_workers:sleep_worker",
            "ZOO_TPU_ARGS": json.dumps([str(tmp_path)]),
            "ZOO_TPU_PARENT": str(launcher.pid),
        })
        worker = subprocess.Popen(
            [sys.executable, "-m", "analytics_zoo_tpu.cluster.bootstrap"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)
        try:
            assert worker.wait(timeout=30) == 113
        finally:
            if worker.poll() is None:
                worker.kill()

    def test_coordinator_handoff_waits_for_atomic_write(self, tmp_path):
        """read_coordinator polls through absent AND torn states until
        the supervisor's atomic publish lands — the fresh-port-per-
        generation handoff the elastic restart path rides on."""
        import threading
        from analytics_zoo_tpu.cluster.bootstrap import read_coordinator
        coord_file = str(tmp_path / "coordinator.json")
        with open(coord_file, "w") as f:
            f.write('{"coord": ')  # torn: mid-replace snapshot

        def publish():
            import time
            time.sleep(0.3)
            tmp = coord_file + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"coord": "127.0.0.1:4242", "generation": 3}, f)
            os.replace(tmp, coord_file)

        t = threading.Thread(target=publish)
        t.start()
        try:
            assert read_coordinator(coord_file,
                                    timeout_s=10) == "127.0.0.1:4242"
        finally:
            t.join()

    def test_coordinator_handoff_times_out(self, tmp_path):
        from analytics_zoo_tpu.cluster.bootstrap import read_coordinator
        with pytest.raises(RuntimeError, match="no coordinator address"):
            read_coordinator(str(tmp_path / "never.json"), timeout_s=0.3)


class TestLauncherRestarts:
    @pytest.mark.pod(budget_s=45)
    def test_per_worker_retry_and_budget_exhaustion(self, tmp_path):
        """restarts= relaunches a failed rank in place: a first-attempt
        crash succeeds on attempt 2 with its failure's log tail kept;
        a rank that fails every attempt exhausts the budget and surfaces
        every attempt's evidence."""
        launcher = PodLauncher(num_processes=1, devices_per_process=1,
                               platform="cpu", restarts=1,
                               log_dir=os.path.join(str(tmp_path), "logs"))
        results = launcher.run("tests.pod_workers:flaky_worker",
                               args=[str(tmp_path)], timeout=240)
        assert results[0].returncode == 0
        assert results[0].attempts == 2
        assert len(results[0].attempt_tails) == 1
        assert "first attempt dies" in results[0].attempt_tails[0]

        with pytest.raises(PodLaunchError) as ei:
            launcher.run("tests.pod_workers:always_failing_worker",
                         args=[str(tmp_path)], timeout=240)
        (res,) = ei.value.results
        assert res.attempts == 2  # initial + one retry, both failed
        assert len(res.attempt_tails) == 1
        assert "always failing worker" in res.attempt_tails[0]


class TestSubmitCLI:
    def test_submit_runs_example_across_workers(self):
        """The deploy CLI contract: zoo-tpu-submit --nprocs 2 <example>
        --smoke completes with every worker green."""
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, "-m", "analytics_zoo_tpu.cluster.submit",
             "--nprocs", "2", "--platform", "cpu", "--devices-per-proc", "2",
             os.path.join(repo, "examples", "recommendation",
                          "ncf_example.py"), "--smoke"],
            capture_output=True, text=True, timeout=600, cwd=repo)
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "worker 0: rc=0" in proc.stdout
        assert "worker 1: rc=0" in proc.stdout

    def test_emit_k8s_manifest(self):
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, "-m", "analytics_zoo_tpu.cluster.submit",
             "--nprocs", "3", "--emit", "k8s", "--image", "zoo:v1",
             "train.py", "--epochs", "2"],
            capture_output=True, text=True, timeout=60, cwd=repo)
        assert proc.returncode == 0, proc.stderr[-500:]
        out = proc.stdout
        assert out.count("kind: Job") == 3
        assert "ZOO_TPU_NPROCS, value: '3'" in out
        assert "zoo:v1" in out
        assert "'--epochs', '2'" in out


class TestMultiHostDirectEval:
    @pytest.mark.pod(budget_s=30)
    def test_direct_eval_counts_tails(self, tmp_path):
        launcher = PodLauncher(num_processes=2, devices_per_process=2,
                               platform="cpu",
                               log_dir=os.path.join(str(tmp_path), "logs"))
        launcher.run("tests.pod_workers:direct_eval_tail_worker",
                     args=[str(tmp_path)], timeout=300)
        import json
        losses = []
        for rank in range(2):
            with open(os.path.join(str(tmp_path), f"eval_{rank}.json")) as f:
                losses.append(json.load(f)["loss"])
        # one logical eval: both hosts must agree on the weighted loss
        assert losses[0] == pytest.approx(losses[1])

    @pytest.mark.pod(budget_s=30)
    def test_exact_eval_matches_single_process(self, tmp_path):
        """Per-example masked eval on ragged 2-host shards equals the
        single-process loss over the concatenated data (zero tail bias) —
        the worker asserts the equality in-process; here we also check
        both hosts agreed."""
        launcher = PodLauncher(num_processes=2, devices_per_process=2,
                               platform="cpu",
                               log_dir=os.path.join(str(tmp_path), "logs"))
        launcher.run("tests.pod_workers:exact_eval_worker",
                     args=[str(tmp_path)], timeout=300)
        import json
        vals = []
        for rank in range(2):
            with open(os.path.join(str(tmp_path),
                                   f"exact_{rank}.json")) as f:
                vals.append(json.load(f))
        assert vals[0]["loss"] == pytest.approx(vals[1]["loss"])
        assert vals[0]["loss"] == pytest.approx(vals[0]["expect"],
                                                abs=1e-5)
