"""PodLauncher multi-process orchestration: 2 coordinated workers on the CPU
backend drive per-host sharding, global-batch training, rank-0 checkpointing,
and failure detection (reference RayOnSpark launch/guard behavior,
``pyzoo/zoo/ray/raycontext.py:190``)."""
import glob
import json
import os

import numpy as np
import pytest

from analytics_zoo_tpu.cluster import PodLaunchError, PodLauncher


class TestPodTraining:
    def test_two_process_train(self, tmp_path):
        workdir = str(tmp_path)
        launcher = PodLauncher(num_processes=2, devices_per_process=2,
                               platform="cpu", log_dir=os.path.join(workdir, "logs"))
        results = launcher.run("tests.pod_workers:train_worker",
                               args=[workdir], timeout=600)
        assert [r.returncode for r in results] == [0, 0]

        reports = {}
        for path in glob.glob(os.path.join(workdir, "done_*.json")):
            with open(path) as f:
                r = json.load(f)
            reports[r["process_index"]] = r
        assert set(reports) == {0, 1}

        # per-host shards are disjoint and cover the dataset
        rows0 = set(reports[0]["shard_rows"])
        rows1 = set(reports[1]["shard_rows"])
        assert rows0.isdisjoint(rows1)
        assert rows0 | rows1 == set(float(i) for i in range(32))

        # synchronous data parallelism: both processes observed the same loss
        assert reports[0]["final_loss"] == pytest.approx(
            reports[1]["final_loss"], abs=1e-6)
        assert reports[0]["iterations"] == reports[1]["iterations"] == 8

        # checkpointing is rank-0-only: exactly one process wrote snapshots
        ckpts = glob.glob(os.path.join(workdir, "ckpt", "*"))
        assert ckpts, "rank 0 wrote no checkpoint"

    def test_failure_detection_kills_pod(self, tmp_path):
        """One dead worker must fail the job fast, not hang the collective."""
        launcher = PodLauncher(num_processes=2, devices_per_process=1,
                               platform="cpu",
                               log_dir=os.path.join(str(tmp_path), "logs"))
        with pytest.raises(PodLaunchError) as ei:
            launcher.run("tests.pod_workers:failing_worker",
                         args=[str(tmp_path)], timeout=120)
        # rank 1 raised; rank 0 (blocked in allgather) was terminated
        assert "workers failed" in str(ei.value) or "timed out" in str(ei.value)

    def test_bad_target_rejected(self):
        from analytics_zoo_tpu.cluster.bootstrap import resolve_target
        with pytest.raises(ValueError):
            resolve_target("no_colon_here")
