"""Caffe import: prototxt parsing, caffemodel (binary protobuf) weights,
and end-to-end numeric parity with a torch re-implementation."""
import struct

import numpy as np
import pytest

from analytics_zoo_tpu.net.caffe_loader import (
    load_caffe, load_caffemodel_weights, parse_prototxt)

# -- tiny NetParameter binary encoder (test-side twin of the decoder) -------


def _varint(v):
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _len_field(fno, payload):
    return _varint((fno << 3) | 2) + _varint(len(payload)) + payload


def _str_field(fno, s):
    return _len_field(fno, s.encode())


def _blob(arr):
    arr = np.asarray(arr, np.float32)
    shape = _len_field(7, b"".join(_varint((1 << 3) | 0) + _varint(d)
                                   for d in arr.shape))
    data = _len_field(5, arr.astype("<f4").tobytes())
    return shape + data


def _layer(name, blobs):
    body = _str_field(1, name) + _str_field(2, "x")
    body += b"".join(_len_field(7, _blob(b)) for b in blobs)
    return _len_field(100, body)


PROTOTXT = """
name: "tiny"  # a comment
input: "data"
input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 1 }
}
layer {
  name: "bn1" type: "BatchNorm" bottom: "conv1" top: "bn1"
  batch_norm_param { eps: 1e-5 use_global_stats: true }
}
layer { name: "scale1" type: "Scale" bottom: "bn1" top: "bn1s"
        scale_param { bias_term: true } }
layer { name: "relu1" type: "ReLU" bottom: "bn1s" top: "relu1" }
layer {
  name: "pool1" type: "Pooling" bottom: "relu1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "fc1" type: "InnerProduct" bottom: "pool1" top: "fc1"
  inner_product_param { num_output: 5 }
}
layer { name: "prob" type: "Softmax" bottom: "fc1" top: "prob" }
"""


class TestPrototxtParser:
    def test_parse_structure(self):
        net = parse_prototxt(PROTOTXT)
        assert net["name"] == "tiny"
        assert net["input"] == "data"
        layers = net["layer"]
        assert [l["type"] for l in layers] == [
            "Convolution", "BatchNorm", "Scale", "ReLU", "Pooling",
            "InnerProduct", "Softmax"]
        assert layers[0]["convolution_param"]["num_output"] == 4
        assert layers[4]["pooling_param"]["pool"] == "MAX"
        assert layers[1]["batch_norm_param"]["use_global_stats"] is True
        assert net["input_shape"]["dim"] == [1, 3, 8, 8]

    def test_unbalanced_braces_rejected(self):
        with pytest.raises(ValueError):
            parse_prototxt("layer { name: \"x\" ")


class TestCaffeEndToEnd:
    def _weights(self, rs):
        return {
            "conv1": [rs.randn(4, 3, 3, 3).astype(np.float32),
                      rs.randn(4).astype(np.float32)],
            "bn1": [rs.rand(4).astype(np.float32),           # mean*factor
                    (rs.rand(4) + 0.5).astype(np.float32),   # var*factor
                    np.asarray([2.0], np.float32)],          # scale factor
            "scale1": [(rs.rand(4) + 0.5).astype(np.float32),
                       rs.randn(4).astype(np.float32)],
            "fc1": [rs.randn(5, 4 * 4 * 4).astype(np.float32),
                    rs.randn(5).astype(np.float32)],
        }

    def _write_model(self, tmp_path, weights):
        data = _str_field(1, "tiny")
        for name, blobs in weights.items():
            data += _layer(name, blobs)
        pt = tmp_path / "net.prototxt"
        cm = tmp_path / "net.caffemodel"
        pt.write_text(PROTOTXT)
        cm.write_bytes(data)
        return str(pt), str(cm)

    def test_weights_decode(self, tmp_path):
        rs = np.random.RandomState(0)
        weights = self._weights(rs)
        _, cm = self._write_model(tmp_path, weights)
        loaded = load_caffemodel_weights(cm)
        assert set(loaded) == set(weights)
        np.testing.assert_allclose(loaded["conv1"][0], weights["conv1"][0],
                                   rtol=1e-6)
        assert loaded["fc1"][0].shape == (5, 64)

    def test_matches_torch(self, tmp_path):
        torch = pytest.importorskip("torch")
        nn = torch.nn
        rs = np.random.RandomState(1)
        weights = self._weights(rs)
        pt, cm = self._write_model(tmp_path, weights)
        model, params, state = load_caffe(pt, cm)

        # torch twin with the same weights
        tm = nn.Sequential(
            nn.Conv2d(3, 4, 3, padding=1), nn.BatchNorm2d(4, eps=1e-5),
            nn.ReLU(), nn.MaxPool2d(2, 2), nn.Flatten(), nn.Linear(64, 5),
            nn.Softmax(dim=-1))
        with torch.no_grad():
            tm[0].weight.copy_(torch.from_numpy(weights["conv1"][0]))
            tm[0].bias.copy_(torch.from_numpy(weights["conv1"][1]))
            factor = float(weights["bn1"][2][0])
            tm[1].running_mean.copy_(
                torch.from_numpy(weights["bn1"][0] / factor))
            tm[1].running_var.copy_(
                torch.from_numpy(weights["bn1"][1] / factor))
            tm[1].weight.copy_(torch.from_numpy(weights["scale1"][0]))
            tm[1].bias.copy_(torch.from_numpy(weights["scale1"][1]))
            tm[5].weight.copy_(torch.from_numpy(weights["fc1"][0]))
            tm[5].bias.copy_(torch.from_numpy(weights["fc1"][1]))
        tm.eval()

        x = rs.randn(2, 3, 8, 8).astype(np.float32)
        with torch.no_grad():
            expected = tm(torch.from_numpy(x)).numpy()
        got, _ = model.call(params, state, np.transpose(x, (0, 2, 3, 1)),
                            training=False)
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-3,
                                   atol=1e-4)

    def test_stacked_ceil_poolings(self, tmp_path):
        """Caffe ceil-mode sizing must propagate through cascaded pools:
        8 →(k3,s2 ceil)→ 4 →(k3,s2 ceil)→ 2 (floor would give 3 → 1)."""
        pt = tmp_path / "pools.prototxt"
        pt.write_text("""
input: "data"
input_shape { dim: 1 dim: 1 dim: 8 dim: 8 }
layer { name: "p1" type: "Pooling" bottom: "data" top: "p1"
        pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "p2" type: "Pooling" bottom: "p1" top: "p2"
        pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
""")
        model, params, state = load_caffe(str(pt))
        x = np.arange(64, dtype=np.float32).reshape(1, 8, 8, 1)
        y, _ = model.call(params, state, x)
        assert np.asarray(y).shape == (1, 2, 2, 1)

    def test_inplace_final_layer(self, tmp_path):
        """Caffe's in-place idiom (top == bottom) on the LAST layer must
        still yield a network output."""
        pt = tmp_path / "inplace.prototxt"
        pt.write_text("""
input: "data"
input_shape { dim: 1 dim: 1 dim: 4 dim: 4 }
layer { name: "p1" type: "Pooling" bottom: "data" top: "p1"
        pooling_param { pool: AVE kernel_size: 2 stride: 2 } }
layer { name: "relu1" type: "ReLU" bottom: "p1" top: "p1" }
""")
        model, params, state = load_caffe(str(pt))
        x = -np.ones((1, 4, 4, 1), np.float32)
        y, _ = model.call(params, state, x)
        assert np.asarray(y).shape == (1, 2, 2, 1)
        np.testing.assert_array_equal(np.asarray(y), 0.0)  # relu applied

    def test_ave_pool_ceil_matches_torch(self, tmp_path):
        """Caffe AVE pooling: ceil sizing + divisor clipped at size+pad —
        torch's AvgPool2d(ceil_mode=True, count_include_pad=True) implements
        the same contract."""
        torch = pytest.importorskip("torch")
        pt = tmp_path / "ave.prototxt"
        pt.write_text("""
input: "data"
input_shape { dim: 1 dim: 1 dim: 6 dim: 6 }
layer { name: "p1" type: "Pooling" bottom: "data" top: "p1"
        pooling_param { pool: AVE kernel_size: 3 stride: 2 pad: 1 } }
""")
        model, params, state = load_caffe(str(pt))
        x = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
        with torch.no_grad():
            expected = torch.nn.AvgPool2d(
                3, 2, padding=1, ceil_mode=True, count_include_pad=True)(
                torch.from_numpy(x)).numpy()
        y, _ = model.call(params, state, np.transpose(x, (0, 2, 3, 1)))
        np.testing.assert_allclose(np.transpose(np.asarray(y), (0, 3, 1, 2)),
                                   expected, rtol=1e-5)

    def test_rectangular_pooling(self, tmp_path):
        pt = tmp_path / "rect.prototxt"
        pt.write_text("""
input: "data"
input_shape { dim: 1 dim: 1 dim: 8 dim: 9 }
layer { name: "p1" type: "Pooling" bottom: "data" top: "p1"
        pooling_param { pool: MAX kernel_h: 2 kernel_w: 3
                        stride_h: 2 stride_w: 3 } }
""")
        model, params, state = load_caffe(str(pt))
        x = np.arange(72, dtype=np.float32).reshape(1, 8, 9, 1)
        y, _ = model.call(params, state, x)
        assert np.asarray(y).shape == (1, 4, 3, 1)
        assert np.asarray(y)[0, 0, 0, 0] == x[0, 0:2, 0:3, 0].max()

    def test_unpaired_batchnorm_rejected(self, tmp_path):
        pt = tmp_path / "bn.prototxt"
        pt.write_text("""
input: "data"
input_shape { dim: 1 dim: 1 dim: 4 dim: 4 }
layer { name: "bn" type: "BatchNorm" bottom: "data" top: "bn" }
""")
        cm = tmp_path / "bn.caffemodel"
        cm.write_bytes(_str_field(1, "n") + _layer("bn", [
            np.zeros(1, np.float32), np.ones(1, np.float32),
            np.ones(1, np.float32)]))
        with pytest.raises(Exception, match="Scale"):
            load_caffe(str(pt), str(cm))

    def test_missing_weights_rejected(self, tmp_path):
        pt = tmp_path / "net.prototxt"
        pt.write_text(PROTOTXT)
        with pytest.raises(Exception, match="caffemodel"):
            load_caffe(str(pt))
