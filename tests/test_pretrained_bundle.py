"""Pretrained bundle format: one artifact = weights + config + label map +
preprocessing spec, over the scheme-aware IO (reference ships label maps and
per-model preproc with each pretrained artifact —
``ImageClassifier.scala:37``, ``ObjectDetectionConfig.scala:1``)."""
import json

import numpy as np
import pytest

from analytics_zoo_tpu.common import file_io
from analytics_zoo_tpu.models import (DETECTION_CONFIGS, ObjectDetector,
                                      ZooModel, detection_config)
from analytics_zoo_tpu.models.image.imageclassification import ImageClassifier


@pytest.fixture()
def remote_root():
    from fsspec.implementations.memory import MemoryFileSystem
    file_io.register_filesystem("fakegs", MemoryFileSystem())
    import uuid
    yield f"fakegs://bundles-{uuid.uuid4().hex[:8]}"
    file_io.unregister_filesystem("fakegs")


def _tiny_classifier():
    clf = ImageClassifier("resnet18", num_classes=3,
                          input_shape=(32, 32, 3),
                          labels=["cat", "dog", "bird"])
    clf._ensure_built()
    clf.default_compile()
    clf.predict(np.random.RandomState(0).rand(2, 32, 32, 3)
                .astype(np.float32), batch_size=2)  # materialize params
    return clf


class TestBundleRoundTrip:
    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_remote_bundle_predicts_with_labels(self, ctx, remote_root):
        """Save to a fake-remote URI, load back, predict with label names
        through the bundled preprocessing — the full user journey."""
        clf = _tiny_classifier()
        uri = file_io.join(remote_root, "resnet18-tiny")
        clf.save_pretrained(uri)
        assert file_io.exists(file_io.join(uri, "zoo_bundle.json"))

        loaded = ZooModel.load_pretrained(uri)
        assert isinstance(loaded, ImageClassifier)
        assert loaded.labels == ["cat", "dog", "bird"]

        from analytics_zoo_tpu.feature.image import ImageSet
        rs = np.random.RandomState(1)
        imgs = [rs.randint(0, 255, (48, 40, 3)).astype(np.uint8)
                for _ in range(3)]
        preds = loaded.predict_image_set(ImageSet.from_arrays(imgs), top_k=2)
        assert len(preds) == 3
        for row in preds:
            assert len(row) == 2
            for label, prob in row:
                assert label in {"cat", "dog", "bird"}
                assert 0.0 <= prob <= 1.0

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_bundle_predictions_bitmatch_source(self, ctx, tmp_path):
        clf = _tiny_classifier()
        x = np.random.RandomState(2).rand(4, 32, 32, 3).astype(np.float32)
        want = np.asarray(clf.predict(x, batch_size=4))
        clf.save_pretrained(str(tmp_path / "bundle"))
        loaded = ZooModel.load_pretrained(str(tmp_path / "bundle"))
        got = np.asarray(loaded.predict(x, batch_size=4))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_bundle_json_carries_preprocessing_spec(self, ctx, tmp_path):
        clf = _tiny_classifier()
        clf.save_pretrained(str(tmp_path / "b"))
        bundle = json.loads((tmp_path / "b" / "zoo_bundle.json").read_text())
        assert bundle["format"] == "zoo-tpu-bundle/1"
        ops = [s["op"] for s in bundle["preprocessing"]]
        assert ops == ["resize", "channel_normalize", "to_sample"]
        assert bundle["preprocessing"][0]["height"] == 32
        assert bundle["labels"] == ["cat", "dog", "bird"]

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_load_pretrained_rejects_bare_checkpoint(self, ctx, tmp_path):
        clf = _tiny_classifier()
        clf.save_model(str(tmp_path / "plain"))
        with pytest.raises(Exception):
            ZooModel.load_pretrained(str(tmp_path / "plain"))


class TestDetectionConfigRegistry:
    def test_registry_has_published_variants(self):
        assert {"ssd-vgg16-300x300", "ssd-vgg16-512x512",
                "ssd-mobilenet-300x300"} <= set(DETECTION_CONFIGS)
        cfg = detection_config("ssd-vgg16-300x300")
        assert cfg["preprocess"]["mean"] == [123.0, 117.0, 104.0]
        assert cfg["postprocess"]["iou_threshold"] == 0.45
        with pytest.raises(ValueError):
            detection_config("ssd-made-up")

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_from_detection_config_builds_and_bundles(self, ctx, tmp_path):
        det = ObjectDetector.from_detection_config(
            "ssd-mobilenet-300x300", class_num=4,
            labels=["bg", "person", "car", "dog"])
        assert det.backbone == "mobilenet" and det.resolution == 300
        spec = det.preprocessing_spec()
        assert spec[1]["mean"] == [127.5, 127.5, 127.5]
        det._ensure_built()
        det.default_compile()
        x = np.random.RandomState(0).rand(1, 300, 300, 3).astype(np.float32)
        det.predict(x, batch_size=1)
        det.save_pretrained(str(tmp_path / "ssd"))
        loaded = ZooModel.load_pretrained(str(tmp_path / "ssd"))
        assert loaded.labels == ["bg", "person", "car", "dog"]
        boxes, scores, classes = loaded.detect(x, batch_size=1)
        assert boxes.shape[0] == 1 and boxes.shape[2] == 4

    @pytest.mark.slow  # re-tiered: heaviest e2e sweep (tier-1 870s budget)
    def test_predict_image_set_uses_variant_postprocess(self, ctx):
        det = ObjectDetector.from_detection_config("ssd-vgg16-300x300",
                                                   class_num=3)
        det._ensure_built()
        det.default_compile()
        from analytics_zoo_tpu.feature.image import ImageSet
        rs = np.random.RandomState(3)
        imgs = [rs.randint(0, 255, (320, 280, 3)).astype(np.uint8)]
        boxes, scores, classes = det.predict_image_set(
            ImageSet.from_arrays(imgs), max_detections=7)
        assert boxes.shape[1] == 7 * (det.class_num - 1) or \
            boxes.shape[1] <= 7 * det.class_num
