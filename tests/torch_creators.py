"""Module-level creator functions for the TorchTrainer tests (the pickled
creator contract requires importable module-level functions — same constraint
Ray's cloudpickle puts on the reference's MXNetTrainer creators)."""
import numpy as np

W_TRUE = np.array([[2.0], [-3.0]], dtype=np.float32)


def make_model():
    import torch
    torch.manual_seed(7)
    return torch.nn.Linear(2, 1)


def make_optimizer(model):
    import torch
    return torch.optim.SGD(model.parameters(), lr=0.2)


def make_loss():
    import torch
    return torch.nn.MSELoss()


def make_data(rank, world):
    rs = np.random.RandomState(100 + rank)  # disjoint shards per rank
    x = rs.rand(64, 2).astype(np.float32)
    y = x @ W_TRUE + 0.5
    return [(x[i:i + 16], y[i:i + 16]) for i in range(0, 64, 16)]


def _fixed_xy():
    rs = np.random.RandomState(3)
    x = rs.rand(32, 2).astype(np.float32)
    y = (x @ W_TRUE).astype(np.float32)
    return x, y


def data_halves(rank, world):
    x, y = _fixed_xy()
    n = len(x) // world
    lo = rank * n
    return [(x[lo:lo + n], y[lo:lo + n])]


def data_full(rank, world):
    x, y = _fixed_xy()
    return [(x, y)]
