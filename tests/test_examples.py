"""Every example must run end-to-end in --smoke mode (the reference ships
runnable examples under pyzoo/zoo/examples; these are the CI-checked
equivalents)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the two heaviest smokes (~90s combined) run in the slow tier; their
# subject matter keeps tier-1 coverage through test_objectdetection.py /
# test_int8_dataflow.py
_SLOW = {
    "examples/imageclassification/int8_dataflow_train.py",
    "examples/objectdetection/ssd_example.py",
    # heaviest smokes re-tiered for the tier-1 870s budget
    "examples/textgeneration/lm_generate_example.py",
    "examples/textclassification/bert_classifier_example.py",
    "examples/imageclassification/pretrained_import.py",
    "examples/imageclassification/resnet_transfer.py",
    "examples/parallel/moe_pipeline_example.py",
    "examples/seq2seq/chatbot_example.py",
    "examples/inference/quantized_inference_example.py",
}

EXAMPLES = [
    "examples/recommendation/ncf_example.py",
    "examples/recommendation/wide_and_deep_example.py",
    "examples/imageclassification/resnet_transfer.py",
    "examples/imageclassification/pretrained_import.py",
    "examples/imageclassification/int8_dataflow_train.py",
    "examples/textclassification/bert_classifier_example.py",
    "examples/tfrecord/tfrecord_train.py",
    "examples/serving/serving_example.py",
    "examples/zouwu/forecast_example.py",
    "examples/cluster/pod_train.py",
    "examples/parallel/moe_pipeline_example.py",
    "examples/objectdetection/ssd_example.py",
    "examples/anomalydetection/anomaly_example.py",
    "examples/seq2seq/chatbot_example.py",
    "examples/automl/autots_example.py",
    "examples/nnframes/nn_classifier_example.py",
    "examples/gan/gan_example.py",
    "examples/inference/quantized_inference_example.py",
    "examples/xshard/xshard_example.py",
    "examples/longcontext/long_context_example.py",
    "examples/textgeneration/lm_generate_example.py",
]


# examples whose --smoke path needs an optional extra (pyproject extras)
_NEEDS = {"examples/imageclassification/pretrained_import.py": "torch"}


@pytest.mark.parametrize(
    "script",
    [pytest.param(p, marks=[pytest.mark.slow] if p in _SLOW else [])
     for p in EXAMPLES],
    ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_smoke(script):
    if script in _NEEDS:
        pytest.importorskip(_NEEDS[script])
    env = dict(os.environ)
    # examples assume `pip install analytics-zoo-tpu`; in-tree CI runs them
    # against the checkout instead
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # force the CPU backend the way conftest does — via jax.config, BEFORE
    # the script runs. The env-var route (JAX_PLATFORMS=cpu) is NOT enough:
    # a sitecustomize-registered hardware plugin overrides it at interpreter
    # start, so example children would initialize the remote-TPU backend —
    # and hang for their full timeout whenever that tunnel is unhealthy
    # (observed: the "CPU smoke" examples were in fact running over the
    # tunnel whenever it was up)
    path = os.path.join(REPO, script)
    boot = ("import jax, runpy, sys; "
            "jax.config.update('jax_platforms', 'cpu'); "
            f"sys.argv = [{path!r}, '--smoke']; "
            f"runpy.run_path({path!r}, run_name='__main__')")
    proc = subprocess.run(
        [sys.executable, "-c", boot],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"{script} failed:\n--- stdout ---\n{proc.stdout[-2000:]}\n"
        f"--- stderr ---\n{proc.stderr[-2000:]}")
    assert proc.stdout.strip(), f"{script} produced no output"
