"""Estimator end-to-end tests on the 8-device CPU mesh (reference strategy:
distributed-loop semantics on a simulated multi-node local master)."""
import os

import jax
import numpy as np
import pytest

from analytics_zoo_tpu.common.triggers import MaxIteration, SeveralIteration
from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
from analytics_zoo_tpu.keras.layers import Dense
from analytics_zoo_tpu.utils.tensorboard import read_scalars


def make_regression(n=256, d=4, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, 1).astype(np.float32)
    x = rs.randn(n, d).astype(np.float32)
    y = x @ w + 0.01 * rs.randn(n, 1).astype(np.float32)
    return x, y


def make_estimator(metrics=None):
    model = Sequential([Dense(8, activation="tanh"), Dense(1)])
    return Estimator(model=model, loss_fn=objectives.get("mse"),
                     optimizer=optimizers.Adam(1e-2), metrics=metrics or [])


class TestTraining:
    def test_loss_decreases(self, ctx):
        x, y = make_regression()
        est = make_estimator()
        fs = FeatureSet.from_ndarrays(x, y, seed=1)
        result = est.train(fs, batch_size=64, epochs=10)
        h = result["loss_history"]
        assert h[-1] < h[0] * 0.5
        assert result["iterations"] == 10 * (256 // 64)

    def test_end_trigger_max_iteration(self, ctx):
        x, y = make_regression()
        est = make_estimator()
        fs = FeatureSet.from_ndarrays(x, y)
        result = est.train(fs, batch_size=64, end_trigger=MaxIteration(7))
        assert result["iterations"] == 7

    def test_multi_step_dispatch_matches_single(self, ctx):
        """steps_per_dispatch>1 scans K steps in one dispatch; same data
        order + same per-step rng schedule must reproduce the single-step
        loss trajectory EXACTLY (and handle the 4,4,2 epoch-tail group)."""
        x, y = make_regression(n=640, d=16)
        h1 = make_estimator().train(
            FeatureSet.from_ndarrays(x, y, shuffle=False),
            batch_size=64, epochs=3)
        est2 = make_estimator()
        h2 = est2.train(FeatureSet.from_ndarrays(x, y, shuffle=False),
                        batch_size=64, epochs=3, steps_per_dispatch=4)
        assert est2.global_step == 30
        assert len(h2["loss_history"]) == 30
        np.testing.assert_allclose(h1["loss_history"], h2["loss_history"],
                                   rtol=0, atol=0)

    def test_multi_step_dispatch_trigger_quantized(self, ctx):
        """MaxIteration may overshoot by < K within one dispatch group."""
        x, y = make_regression()
        est = make_estimator()
        fs = FeatureSet.from_ndarrays(x, y)
        result = est.train(fs, batch_size=64, end_trigger=MaxIteration(3),
                           steps_per_dispatch=2)
        assert result["iterations"] == 4  # two groups of 2

    def test_evaluate_and_predict(self, ctx):
        x, y = make_regression(n=100)
        est = make_estimator(metrics=["mae", "mse"])
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=32, epochs=40)
        scores = est.evaluate(FeatureSet.from_ndarrays(x, y, shuffle=False),
                              batch_size=32)
        assert set(scores) == {"mae", "mse"}
        assert scores["mse"] < 0.5
        preds = est.predict(x, batch_size=32)
        assert preds.shape == (100, 1)  # remainder rows preserved
        np.testing.assert_allclose(
            np.mean((preds - y) ** 2), scores["mse"], rtol=0.2, atol=0.05)

    def test_gradient_clipping(self, ctx):
        x, y = make_regression()
        est = make_estimator()
        est.set_gradient_clipping(("l2", 0.1))
        fs = FeatureSet.from_ndarrays(x, y)
        result = est.train(fs, batch_size=64, epochs=2)
        assert result["loss_history"][-1] < result["loss_history"][0]

    def test_validation_during_training(self, ctx):
        x, y = make_regression()
        est = make_estimator(metrics=["mae"])
        fs = FeatureSet.from_ndarrays(x, y)
        val = FeatureSet.from_ndarrays(x[:64], y[:64], shuffle=False)
        est.train(fs, batch_size=64, epochs=2, validation_set=val)

    def test_tensorboard_scalars(self, ctx, tmp_path):
        x, y = make_regression()
        est = make_estimator()
        est.set_tensorboard(str(tmp_path), "app")
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=64, epochs=2)
        losses = read_scalars(os.path.join(str(tmp_path), "app", "train"), "Loss")
        assert len(losses) == 8
        lrs = read_scalars(os.path.join(str(tmp_path), "app", "train"),
                           "LearningRate")
        assert lrs[0][1] == pytest.approx(1e-2)
        # per-iteration Throughput (reference getTrainSummary("Throughput"))
        tp = read_scalars(os.path.join(str(tmp_path), "app", "train"),
                          "Throughput")
        assert len(tp) == 8 and all(v > 0 for _, v in tp)

    def test_model_get_train_summary(self, ctx, tmp_path):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        x, y = make_regression()
        m = Sequential([Dense(8, activation="relu"), Dense(1)])
        m.compile(optimizer="adam", loss="mse")
        m.set_tensorboard(str(tmp_path), "app")
        m.fit(x, y, batch_size=64, nb_epoch=2)
        losses = m.get_train_summary("Loss")
        assert len(losses) == 8
        tp = m.get_train_summary("Throughput")
        assert len(tp) == 8 and all(v > 0 for _, v in tp)


class TestCheckpoint:
    def test_save_load_roundtrip(self, ctx, tmp_path):
        x, y = make_regression()
        est = make_estimator()
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=64, epochs=2)
        preds1 = est.predict(x[:64])
        path = str(tmp_path / "ckpt")
        est.save_checkpoint(path)

        est2 = make_estimator()
        est2.load_checkpoint(path)
        preds2 = est2.predict(x[:64])
        np.testing.assert_allclose(preds1, preds2, rtol=1e-5)
        assert est2.global_step == est.global_step

    def test_resume_training(self, ctx, tmp_path):
        x, y = make_regression()
        est = make_estimator()
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=64, epochs=1)
        path = str(tmp_path / "ckpt")
        est.save_checkpoint(path)
        est2 = make_estimator()
        est2.load_checkpoint(path)
        r = est2.train(fs, batch_size=64, epochs=2)  # continues to epoch 2
        assert est2.global_step > est.global_step

    def test_periodic_snapshots(self, ctx, tmp_path):
        x, y = make_regression()
        est = make_estimator()
        est.set_checkpoint(str(tmp_path), SeveralIteration(2))
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=64, epochs=1)  # 4 iterations
        snaps = [d for d in os.listdir(tmp_path) if d.startswith("snapshot-")]
        assert len(snaps) == 2  # at iterations 2 and 4

    def test_periodic_snapshots_multi_step_dispatch(self, ctx, tmp_path):
        """Non-aligned interval under steps_per_dispatch: boundary
        crossings quantize to the group boundary instead of being skipped
        (interval 3, width 2 over 8 steps: boundary 3 fires at check 4,
        boundary 6 at check 6; boundary 9 is past the epoch)."""
        x, y = make_regression(n=512)
        est = make_estimator()
        est.set_checkpoint(str(tmp_path), SeveralIteration(3))
        fs = FeatureSet.from_ndarrays(x, y)
        est.train(fs, batch_size=64, epochs=1, steps_per_dispatch=2)
        snaps = sorted(
            int(d.split("-")[1]) for d in os.listdir(tmp_path)
            if d.startswith("snapshot-"))
        assert snaps == [4, 6], snaps


class TestKerasFacade:
    def test_compile_fit_evaluate(self, ctx):
        x, y = make_regression(n=128)
        model = Sequential([Dense(8, activation="tanh"), Dense(1)])
        model.compile(optimizer="adam", loss="mse", metrics=["mae"])
        model.fit(x, y, batch_size=32, nb_epoch=5)
        scores = model.evaluate(x, y, batch_size=32)
        assert "mae" in scores
        preds = model.predict(x)
        assert preds.shape == (128, 1)

    def test_get_set_weights(self, ctx):
        x, y = make_regression(n=64)
        model = Sequential([Dense(4), Dense(1)])
        model.compile(optimizer="sgd", loss="mse")
        model.fit(x, y, batch_size=32, nb_epoch=1)
        w = model.get_weights()
        preds1 = model.predict(x)
        model.set_weights(jax.tree_util.tree_map(lambda a: a * 0.0, w))
        preds_zero = model.predict(x)
        np.testing.assert_allclose(preds_zero, 0.0, atol=1e-6)
        model.set_weights(w)
        np.testing.assert_allclose(model.predict(x), preds1, rtol=1e-6)


class TestPredictClasses:
    def test_categorical_and_binary(self, ctx):
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        x, y = make_regression(n=64)
        m = Sequential([Dense(8, activation="relu"),
                        Dense(3, activation="softmax")])
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        m.fit(x, (np.abs(y[:, 0]) % 3).astype(np.float32), batch_size=32,
              nb_epoch=1)
        probs = np.asarray(m.predict(x))
        cls = m.predict_classes(x)
        np.testing.assert_array_equal(cls, probs.argmax(-1))
        one_based = m.predict_classes(x, zero_based_label=False)
        np.testing.assert_array_equal(one_based, cls + 1)

        mb = Sequential([Dense(4, activation="relu"),
                         Dense(1, activation="sigmoid")])
        mb.compile(optimizer="adam", loss="binary_crossentropy")
        mb.fit(x, (y[:, 0] > 0).astype(np.float32), batch_size=32,
               nb_epoch=1)
        cls_b = mb.predict_classes(x)
        np.testing.assert_array_equal(
            cls_b, (np.asarray(mb.predict(x))[:, 0] > 0.5).astype(int))


class TestMixedPrecision:
    def test_bf16_compute_dtype_trains(self, ctx):
        import jax.numpy as jnp
        import numpy as np
        from analytics_zoo_tpu.estimator import Estimator
        from analytics_zoo_tpu.feature import FeatureSet
        from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
        from analytics_zoo_tpu.keras.layers import BatchNormalization, Dense

        model = Sequential([Dense(16, activation="relu"),
                            BatchNormalization(), Dense(2)])
        est = Estimator(model=model,
                        loss_fn=objectives.get("sparse_categorical_crossentropy"),
                        optimizer=optimizers.Adam(1e-2),
                        compute_dtype=jnp.bfloat16)
        rs = np.random.RandomState(0)
        x = rs.randn(64, 8).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.float32)
        fs = FeatureSet.from_ndarrays(x, y)
        result = est.train(fs, batch_size=16, epochs=3)
        # params stay f32 (master weights), loss decreases
        import jax
        for leaf in jax.tree_util.tree_leaves(est.params):
            assert leaf.dtype == jnp.float32
        assert result["loss_history"][-1] < result["loss_history"][0]
        preds = est.predict(x, batch_size=16)
        assert np.asarray(preds).dtype == np.float32

    def test_bf16_transformer_stack(self, ctx):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from analytics_zoo_tpu.keras.layers import BERT
        bert = BERT(vocab=50, hidden_size=16, n_block=1, n_head=2,
                    max_position_len=8, intermediate_size=32,
                    output_all_block=False, compute_dtype=jnp.bfloat16)
        params, state = bert.build(jax.random.PRNGKey(0), (None, 8))
        tokens = jnp.ones((2, 8), jnp.int32)
        types = jnp.zeros((2, 8), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(8), (2, 8))
        mask = jnp.ones((2, 8))
        (states, pooled), _ = bert.call(params, state,
                                        [tokens, types, pos, mask])
        assert states.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(pooled, np.float32)).all()
