"""Attention kernels + sequence-parallel (ring/Ulysses) tests.

Correctness contract: blockwise/flash/ring/ulysses all reproduce the plain
XLA reference ``dot_product_attention`` (the reference framework's test
strategy of numerical-equivalence checks, SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.ops.attention import (
    blockwise_attention, dot_product_attention, flash_attention)

RNG = jax.random.PRNGKey(7)


def make_qkv(b=2, h=4, s=64, d=16):
    kq, kk, kv = jax.random.split(RNG, 3)
    return (jax.random.normal(kq, (b, h, s, d)),
            jax.random.normal(kk, (b, h, s, d)),
            jax.random.normal(kv, (b, h, s, d)))


class TestBlockwise:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = make_qkv()
        ref = dot_product_attention(q, k, v, causal=causal)
        out = blockwise_attention(q, k, v, causal=causal,
                                  q_block=16, kv_block=16)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_bias(self):
        q, k, v = make_qkv(s=32)
        mask = jnp.ones((2, 1, 1, 32)).at[:, :, :, 20:].set(0.0)
        bias = (1.0 - mask) * -1e9
        ref = dot_product_attention(q, k, v, bias=bias)
        out = blockwise_attention(q, k, v, bias=bias, q_block=8, kv_block=8)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_grad_matches(self):
        q, k, v = make_qkv(b=1, h=2, s=16, d=8)

        def loss_ref(q):
            return dot_product_attention(q, k, v, causal=True).sum()

        def loss_blk(q):
            return blockwise_attention(q, k, v, causal=True,
                                       q_block=4, kv_block=4).sum()

        np.testing.assert_allclose(jax.grad(loss_ref)(q),
                                   jax.grad(loss_blk)(q), atol=2e-5)

    def test_cross_attention_lengths(self):
        kq, kk, kv = jax.random.split(RNG, 3)
        q = jax.random.normal(kq, (2, 2, 24, 8))
        k = jax.random.normal(kk, (2, 2, 40, 8))
        v = jax.random.normal(kv, (2, 2, 40, 8))
        ref = dot_product_attention(q, k, v)
        out = blockwise_attention(q, k, v, q_block=8, kv_block=8)
        np.testing.assert_allclose(out, ref, atol=2e-5)


class TestFlash:
    def test_flash_dispatches_and_matches(self):
        q, k, v = make_qkv()
        ref = dot_product_attention(q, k, v)
        out = flash_attention(q, k, v)  # CPU → blockwise fallback
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_flash_grad(self):
        q, k, v = make_qkv(b=1, h=2, s=16, d=8)
        g1 = jax.grad(lambda q: flash_attention(q, k, v, causal=True).sum())(q)
        g2 = jax.grad(
            lambda q: dot_product_attention(q, k, v, causal=True).sum())(q)
        np.testing.assert_allclose(g1, g2, atol=2e-5)

    def test_jit_compiles(self):
        q, k, v = make_qkv(s=32)
        out = jax.jit(flash_attention, static_argnames=("causal",))(
            q, k, v, causal=True)
        assert out.shape == q.shape


class TestRingAttention:
    def test_ring_matches_reference(self, ctx):
        from jax.sharding import Mesh
        from analytics_zoo_tpu.parallel.ring_attention import (
            ring_self_attention)
        devices = np.asarray(jax.devices()[:4]).reshape(1, 4)
        mesh = Mesh(devices, ("data", "seq"))
        q, k, v = make_qkv(b=2, h=2, s=32, d=8)
        ref = dot_product_attention(q, k, v)
        out = ring_self_attention(mesh, q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)

    def test_ring_causal(self, ctx):
        from jax.sharding import Mesh
        from analytics_zoo_tpu.parallel.ring_attention import (
            ring_self_attention)
        mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(1, 4),
                    ("data", "seq"))
        q, k, v = make_qkv(b=1, h=2, s=16, d=8)
        ref = dot_product_attention(q, k, v, causal=True)
        out = ring_self_attention(mesh, q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)

    def test_ring_grads_flow(self, ctx):
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from analytics_zoo_tpu.parallel.ring_attention import ring_attention
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("seq",))
        q, k, v = make_qkv(b=1, h=2, s=16, d=8)
        spec = P(None, None, "seq", None)

        def loss(q, k, v):
            fn = shard_map(ring_attention, mesh=mesh,
                           in_specs=(spec, spec, spec), out_specs=spec)
            return fn(q, k, v).sum()

        gq = jax.grad(loss)(q, k, v)
        ref_g = jax.grad(
            lambda q: dot_product_attention(q, k, v).sum())(q)
        np.testing.assert_allclose(np.asarray(gq), ref_g, atol=2e-5)


class TestUlysses:
    def test_ulysses_matches_reference(self, ctx):
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from analytics_zoo_tpu.parallel.ring_attention import (
            ulysses_attention)
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("seq",))
        q, k, v = make_qkv(b=2, h=4, s=32, d=8)
        spec = P(None, None, "seq", None)
        fn = shard_map(ulysses_attention, mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
        out = fn(q, k, v)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


class TestTransformerLayers:
    def test_multi_head_attention_layer(self):
        from analytics_zoo_tpu.keras.layers import MultiHeadAttention
        layer = MultiHeadAttention(n_head=4, hidden_size=32)
        params, _ = layer.build(RNG, (None, 10, 32))
        x = jax.random.normal(RNG, (2, 10, 32))
        y, _ = layer.call(params, {}, x)
        assert y.shape == (2, 10, 32)

    def test_transformer_layer_forward(self):
        from analytics_zoo_tpu.keras.layers import TransformerLayer
        layer = TransformerLayer(vocab=50, hidden_size=16, n_block=2,
                                 n_head=2, seq_len=12, output_all_block=False)
        params, _ = layer.build(RNG, [(None, 12), (None, 12)])
        tokens = jnp.ones((2, 12), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(12), (2, 12))
        outs, _ = layer.call(params, {}, [tokens, pos])
        states, pooled = outs
        assert states.shape == (2, 12, 16)
        assert pooled.shape == (2, 16)

    def test_bert_forward_and_mask(self):
        from analytics_zoo_tpu.keras.layers import BERT
        layer = BERT(vocab=60, hidden_size=16, n_block=2, n_head=2,
                     max_position_len=12, intermediate_size=32,
                     output_all_block=True)
        params, _ = layer.build(RNG, [(None, 12)] * 4)
        tokens = jnp.ones((2, 12), jnp.int32)
        types = jnp.zeros((2, 12), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(12), (2, 12))
        mask = jnp.ones((2, 12))
        outs, _ = layer.call(params, {}, [tokens, types, pos, mask])
        assert len(outs) == 3  # 2 block states + pooled
        assert outs[0].shape == (2, 12, 16)
        assert outs[-1].shape == (2, 16)
        # masked positions must not affect unmasked outputs
        mask2 = mask.at[:, 6:].set(0.0)
        tokens2 = tokens.at[:, 6:].set(3)
        out_a, _ = layer.call(params, {}, [tokens, types, pos, mask2])
        out_b, _ = layer.call(params, {}, [tokens2, types, pos, mask2])
        np.testing.assert_allclose(out_a[-1], out_b[-1], atol=1e-5)

    def test_bert_grad(self):
        from analytics_zoo_tpu.keras.layers import BERT
        layer = BERT(vocab=30, hidden_size=8, n_block=1, n_head=2,
                     max_position_len=8, intermediate_size=16,
                     output_all_block=False)
        params, _ = layer.build(RNG, [(None, 8)] * 4)
        tokens = jnp.ones((1, 8), jnp.int32)
        inputs = [tokens, jnp.zeros_like(tokens),
                  jnp.broadcast_to(jnp.arange(8), (1, 8)), jnp.ones((1, 8))]

        def loss(p):
            outs, _ = layer.call(p, {}, inputs)
            return outs[-1].sum()

        g = jax.grad(loss)(params)
        assert g["word_emb"].shape == (30, 8)
        assert float(jnp.abs(g["block_0"]["attn"]["q"]["kernel"]).sum()) > 0


class TestBlockwiseDropout:
    def test_zero_rate_matches_vanilla(self):
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.ops.attention import (
            blockwise_attention, dot_product_attention)
        rs = np.random.RandomState(0)
        q, k, v = [jnp.asarray(rs.randn(2, 2, 16, 8), jnp.float32)
                   for _ in range(3)]
        out = blockwise_attention(q, k, v, dropout_rate=0.0,
                                  dropout_rng=jax.random.PRNGKey(0),
                                  q_block=8, kv_block=8)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_dropout_is_unbiased_post_softmax(self):
        """Streaming per-block dropout must equal standard post-softmax
        dropout in expectation: averaging over many rngs converges to the
        undropped output (the denominator uses undropped weights)."""
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.ops.attention import (
            blockwise_attention, dot_product_attention)
        rs = np.random.RandomState(1)
        q, k, v = [jnp.asarray(rs.randn(1, 1, 8, 4), jnp.float32)
                   for _ in range(3)]
        ref = np.asarray(dot_product_attention(q, k, v))
        sample = jax.jit(lambda key: blockwise_attention(
            q, k, v, dropout_rate=0.3, dropout_rng=key,
            q_block=4, kv_block=4))
        n = 300
        acc = np.zeros_like(ref)
        for i in range(n):
            acc += np.asarray(sample(jax.random.PRNGKey(i)))
        np.testing.assert_allclose(acc / n, ref, atol=0.08)

    def test_dropout_actually_drops(self):
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.ops.attention import blockwise_attention
        rs = np.random.RandomState(2)
        q, k, v = [jnp.asarray(rs.randn(1, 1, 8, 4), jnp.float32)
                   for _ in range(3)]
        a = blockwise_attention(q, k, v, dropout_rate=0.5,
                                dropout_rng=jax.random.PRNGKey(0))
        b = blockwise_attention(q, k, v, dropout_rate=0.5,
                                dropout_rng=jax.random.PRNGKey(1))
        assert np.abs(np.asarray(a) - np.asarray(b)).max() > 1e-3


class TestFlashLse:
    """flash_attention_lse: the merge statistic and its joint gradients
    (the ring hops' building block)."""

    def _qkv(self, S=64, D=16, seed=0):
        rs = np.random.RandomState(seed)
        return tuple(jnp.asarray(rs.randn(2, 3, S, D).astype(np.float32))
                     for _ in range(3))

    def _ref(self, q, k, v, causal):
        import math
        from analytics_zoo_tpu.ops.attention import _NEG_INF
        S = q.shape[-2]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(q.shape[-1])
        if causal:
            qi = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
            ki = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
            s = jnp.where(qi >= ki, s, _NEG_INF)
        return (jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v),
                jax.scipy.special.logsumexp(s, axis=-1))

    @pytest.mark.parametrize("causal", [False, True])
    def test_values_match_reference(self, causal):
        from analytics_zoo_tpu.ops.attention import flash_attention_lse
        q, k, v = self._qkv()
        ro, rl = self._ref(q, k, v, causal)
        fo, fl = flash_attention_lse(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(fo), np.asarray(ro), atol=1e-5)
        np.testing.assert_allclose(np.asarray(fl), np.asarray(rl), atol=1e-5)

    def test_joint_gradients_include_lse_cotangent(self):
        from analytics_zoo_tpu.ops.attention import flash_attention_lse
        q, k, v = self._qkv(seed=3)

        def loss_ref(q, k, v):
            o, l = self._ref(q, k, v, True)
            return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

        def loss_fl(q, k, v):
            o, l = flash_attention_lse(q, k, v, causal=True)
            return jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(jnp.sin(l))

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_causal_ring_gradients(self, ctx):
        """Grads through the switch-based causal ring path (incl. the skip
        branch) match autodiff through the reference."""
        from functools import partial
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from analytics_zoo_tpu.parallel.ring_attention import (
            ring_attention, SEQ_AXIS)
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        seq_mesh = Mesh(np.asarray(jax.devices()[:4]), (SEQ_AXIS,))
        rs = np.random.RandomState(4)
        q, k, v = (jnp.asarray(rs.randn(2, 2, 64, 8).astype(np.float32))
                   for _ in range(3))
        spec = P(None, None, SEQ_AXIS, None)
        ring = shard_map(partial(ring_attention, causal=True),
                         mesh=seq_mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)

        def loss_ring(q, k, v):
            return jnp.sum(ring(q, k, v) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gg = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)


class TestKVCacheDecode:
    """Incremental decoding: prefill + per-token cached attention must
    reproduce full causal attention exactly."""

    def test_incremental_matches_full(self):
        from analytics_zoo_tpu.ops.decode import (
            cached_attention, init_kv_cache)
        rs = np.random.RandomState(0)
        B, H, S, D = 2, 3, 12, 8
        q, k, v = (jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
                   for _ in range(3))
        ref = dot_product_attention(q, k, v, causal=True)

        # prefill the first 5 positions in one block, then decode one by one
        cache = init_kv_cache(B, H, max_len=16, head_dim=D,
                              dtype=jnp.float32)
        out_pre, cache = cached_attention(q[:, :, :5], k[:, :, :5],
                                          v[:, :, :5], cache)
        np.testing.assert_allclose(np.asarray(out_pre),
                                   np.asarray(ref[:, :, :5]), atol=1e-5)
        for i in range(5, S):
            out_i, cache = cached_attention(
                q[:, :, i:i + 1], k[:, :, i:i + 1], v[:, :, i:i + 1], cache)
            np.testing.assert_allclose(np.asarray(out_i[:, :, 0]),
                                       np.asarray(ref[:, :, i]), atol=1e-5)
        assert int(cache["length"]) == S

    def test_greedy_generate_loop(self):
        """A tiny deterministic 'language model': logits prefer token
        (prev + 1) % V; greedy decode must count upward and stop at eos."""
        from analytics_zoo_tpu.ops.decode import greedy_generate
        V = 7

        def step_fn(params, token, cache):
            nxt = (token.astype(jnp.int32) + 1) % V
            logits = jax.nn.one_hot(nxt, V) * 10.0
            return logits, cache

        start = jnp.asarray([0, 4], jnp.int32)
        toks = greedy_generate(step_fn, {}, {}, start, max_new_tokens=6,
                               eos_id=6)
        out = np.asarray(toks)
        # row 0: 1,2,3,4,5,6 ; row 1: 5,6 then padded with eos
        np.testing.assert_array_equal(out[0], [1, 2, 3, 4, 5, 6])
        np.testing.assert_array_equal(out[1], [5, 6, 6, 6, 6, 6])

    def test_generate_with_cached_attention_model(self):
        """End-to-end: a one-layer attention LM decodes under jit with the
        static-shape cache."""
        from analytics_zoo_tpu.ops.decode import (
            cached_attention, greedy_generate, init_kv_cache)
        rs = np.random.RandomState(1)
        V, D, H = 11, 8, 2
        params = {
            "embed": jnp.asarray(rs.randn(V, D).astype(np.float32) * 0.5),
            "wq": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.5),
            "wk": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.5),
            "wv": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.5),
            "out": jnp.asarray(rs.randn(D, V).astype(np.float32) * 0.5),
        }

        def step_fn(p, token, cache):
            x = p["embed"][token.astype(jnp.int32)]  # [B, D]
            def heads(w):
                return (x @ w).reshape(x.shape[0], H, 1, D // H)
            ctx, cache = cached_attention(heads(p["wq"]), heads(p["wk"]),
                                          heads(p["wv"]), cache)
            flat = ctx.reshape(x.shape[0], D)
            return flat @ p["out"], cache

        cache = init_kv_cache(2, H, max_len=8, head_dim=D // H,
                              dtype=jnp.float32)
        start = jnp.asarray([1, 2], jnp.int32)
        gen = jax.jit(lambda p, c, s: greedy_generate(
            step_fn, p, c, s, max_new_tokens=6))
        toks = np.asarray(gen(params, cache, start))
        assert toks.shape == (2, 6)
        assert ((0 <= toks) & (toks < V)).all()

    def test_cache_overflow_raises(self):
        from analytics_zoo_tpu.ops.decode import (
            cached_attention, init_kv_cache)
        rs = np.random.RandomState(2)
        B, H, D = 1, 1, 4
        t = jnp.asarray(rs.randn(B, H, 3, D).astype(np.float32))
        cache = init_kv_cache(B, H, max_len=4, head_dim=D, dtype=jnp.float32)
        _, cache = cached_attention(t, t, t, cache)  # 3 of 4 used
        with pytest.raises(ValueError, match="KV cache overflow"):
            cached_attention(t, t, t, cache)


class TestBeamSearch:
    def test_beam_beats_greedy_on_garden_path(self):
        """Classic garden-path distribution: the greedy first token leads
        to a flat continuation, the runner-up to a peaked one. Beam search
        must find the higher-probability sequence greedy misses."""
        from analytics_zoo_tpu.ops.decode import (
            beam_generate, greedy_generate)
        V = 6
        la, lb = np.log(0.55), np.log(0.45)
        flat = np.log(np.full(V, 1.0 / V))
        peaked = np.log(np.asarray([0.01, 0.01, 0.95, 0.01, 0.01, 0.01]))

        def step_fn(params, token, cache):
            # token 0 -> {1: 0.55, 3: 0.45}; after 1 -> flat; after 3 ->
            # peaked at 2; anything else -> flat
            first = jnp.full((V,), -1e9).at[1].set(la).at[3].set(lb)
            t = token.astype(jnp.int32)
            logits = jnp.where(
                (t == 0)[:, None], first[None],
                jnp.where((t == 3)[:, None], jnp.asarray(peaked)[None],
                          jnp.asarray(flat)[None]))
            return logits, cache

        start = jnp.asarray([0], jnp.int32)
        greedy = np.asarray(greedy_generate(step_fn, {}, {}, start, 2))
        assert greedy[0, 0] == 1  # greedy takes the locally best token
        seqs, scores = beam_generate(step_fn, {}, {}, start, 2, beam_size=3)
        best = np.asarray(seqs)[0, 0]
        # beam finds 0->3->2: log(.45*.95) > log(.55*1/6)
        np.testing.assert_array_equal(best, [3, 2])
        assert np.asarray(scores)[0, 0] == pytest.approx(
            np.log(0.45) + np.log(0.95), abs=1e-4)
        assert (np.asarray(scores)[0, :-1] >= np.asarray(scores)[0, 1:]).all()

    def test_beam_with_cache_model_and_eos(self):
        """Beam over a real cached-attention step_fn: caches reorder by
        backpointer; eos-finished beams pad and keep their score."""
        from analytics_zoo_tpu.ops.decode import (
            beam_generate, cached_attention, init_kv_cache)
        rs = np.random.RandomState(0)
        V, D, H = 8, 8, 2
        params = {
            "embed": jnp.asarray(rs.randn(V, D).astype(np.float32) * 0.5),
            "w": jnp.asarray(rs.randn(D, V).astype(np.float32) * 0.5),
        }

        def step_fn(p, token, cache):
            x = p["embed"][token.astype(jnp.int32)]
            q = x.reshape(x.shape[0], H, 1, D // H)
            ctx, cache = cached_attention(q, q, q, cache)
            return ctx.reshape(x.shape[0], D) @ p["w"], cache

        B = 2
        cache = init_kv_cache(B, H, max_len=8, head_dim=D // H,
                              dtype=jnp.float32)
        start = jnp.asarray([1, 5], jnp.int32)
        seqs, scores = jax.jit(
            lambda p, c, s: beam_generate(step_fn, p, c, s, 4, beam_size=2,
                                          eos_id=0))(params, cache, start)
        assert np.asarray(seqs).shape == (B, 2, 4)
        assert np.asarray(scores).shape == (B, 2)
        assert ((0 <= np.asarray(seqs)) & (np.asarray(seqs) < V)).all()


class TestSampledDecode:
    def _biased_step(self):
        # stationary distribution strongly favoring token 2
        logits_row = jnp.log(jnp.asarray([0.02, 0.02, 0.9, 0.02, 0.02, 0.02]))

        def step_fn(params, token, cache):
            return jnp.tile(logits_row[None], (token.shape[0], 1)), cache
        return step_fn

    def test_temperature_sampling_follows_distribution(self):
        from analytics_zoo_tpu.ops.decode import sample_generate
        step = self._biased_step()
        toks = np.asarray(sample_generate(
            step, {}, {}, jnp.zeros(4, jnp.int32), 64,
            jax.random.PRNGKey(0)))
        assert toks.shape == (4, 64)
        assert (toks == 2).mean() > 0.75  # ~0.9 expected

    def test_top_k_and_top_p_restrict_support(self):
        from analytics_zoo_tpu.ops.decode import sample_generate
        step = self._biased_step()
        t1 = np.asarray(sample_generate(
            step, {}, {}, jnp.zeros(2, jnp.int32), 128,
            jax.random.PRNGKey(1), top_k=1))
        assert (t1 == 2).all()  # only the argmax survives
        tp = np.asarray(sample_generate(
            step, {}, {}, jnp.zeros(2, jnp.int32), 128,
            jax.random.PRNGKey(2), top_p=0.5))
        assert (tp == 2).all()  # nucleus of 0.5 is just token 2 (p=0.9)

    def test_invalid_temperature_raises(self):
        from analytics_zoo_tpu.ops.decode import sample_generate
        with pytest.raises(ValueError, match="temperature"):
            sample_generate(self._biased_step(), {}, {},
                            jnp.zeros(1, jnp.int32), 4,
                            jax.random.PRNGKey(0), temperature=0.0)

    def test_invalid_top_k_top_p_raise(self):
        from analytics_zoo_tpu.ops.decode import sample_generate
        step = self._biased_step()
        with pytest.raises(ValueError, match="top_k"):
            sample_generate(step, {}, {}, jnp.zeros(1, jnp.int32), 4,
                            jax.random.PRNGKey(0), top_k=0)
        with pytest.raises(ValueError, match="top_p"):
            sample_generate(step, {}, {}, jnp.zeros(1, jnp.int32), 4,
                            jax.random.PRNGKey(0), top_p=0.0)
