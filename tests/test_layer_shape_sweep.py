"""Systematic layer sweep: declared vs actual output shapes.

The reference runs a reflective serializer sweep over its whole layer
library (``SerializerSpecHelper.scala`` — SURVEY §4); the analogue for this
functional engine is the SHAPE CONTRACT: ``compute_output_shape`` drives
symbolic graph construction, so a layer whose declaration disagrees with
its ``call`` corrupts every model built with it. This sweep builds one
representative instance per layer family, runs a concrete forward, and
asserts the declared shape (with a None batch dim) matches reality.
"""
import jax
import numpy as np
import pytest

from analytics_zoo_tpu.keras import layers as L

# (constructor thunk, input shape without batch). Batch size is fixed at 4.
CASES = [
    # core
    (lambda: L.Dense(7), (5,)),
    (lambda: L.Dropout(0.5), (5,)),
    (lambda: L.Activation("relu"), (5,)),
    (lambda: L.Flatten(), (3, 4)),
    (lambda: L.Reshape((4, 3)), (12,)),
    (lambda: L.Permute((2, 1)), (3, 4)),
    (lambda: L.RepeatVector(6), (5,)),
    # conv / pooling
    (lambda: L.Convolution1D(6, 3), (10, 4)),
    (lambda: L.Convolution2D(6, 3, 3, border_mode="same"), (8, 8, 3)),
    (lambda: L.Convolution3D(4, 2, 2, 2), (6, 6, 6, 2)),
    (lambda: L.SeparableConvolution2D(6, 3, 3, border_mode="same"),
     (8, 8, 4)),
    (lambda: L.AtrousConvolution2D(5, 3, 3, atrous_rate=(2, 2),
                                   border_mode="same"), (8, 8, 3)),
    (lambda: L.Deconvolution2D(5, 3, 3, subsample=(2, 2)), (5, 5, 3)),
    (lambda: L.MaxPooling2D((2, 2)), (8, 8, 3)),
    (lambda: L.AveragePooling2D((2, 2)), (8, 8, 3)),
    (lambda: L.GlobalAveragePooling2D(), (6, 6, 3)),
    (lambda: L.GlobalMaxPooling2D(), (6, 6, 3)),
    (lambda: L.UpSampling2D((2, 2)), (4, 4, 3)),
    (lambda: L.ZeroPadding2D((1, 1)), (5, 5, 2)),
    (lambda: L.Cropping2D(((1, 1), (1, 1))), (6, 6, 2)),
    # recurrent
    (lambda: L.LSTM(6), (7, 4)),
    (lambda: L.LSTM(6, return_sequences=True), (7, 4)),
    (lambda: L.GRU(5), (7, 4)),
    (lambda: L.SimpleRNN(5), (7, 4)),
    (lambda: L.Bidirectional(L.LSTM(3, return_sequences=True)), (7, 4)),
    (lambda: L.ConvLSTM2D(4, 3, return_sequences=True), (5, 6, 6, 2)),
    # embedding / norm
    (lambda: L.Embedding(20, 6), (7,)),
    (lambda: L.BatchNormalization(), (8,)),
    (lambda: L.LayerNormalization(), (8,)),
    # advanced
    (lambda: L.Masking(0.0), (5, 3)),
    (lambda: L.Highway(), (6,)),
    (lambda: L.MaxoutDense(5, nb_feature=3), (6,)),
    (lambda: L.TimeDistributed(L.Dense(4)), (5, 6)),
    (lambda: L.SpatialDropout2D(0.3), (6, 6, 3)),
    (lambda: L.GaussianNoise(0.1), (5,)),
    (lambda: L.LeakyReLU(0.1), (5,)),
    (lambda: L.PReLU(), (5,)),
    (lambda: L.ELU(), (5,)),
    (lambda: L.ThresholdedReLU(), (5,)),
    # attention / crf
    (lambda: L.CRF(5), (6, 5)),
    # second wave: 1D/3D variants, elementwise, locally connected
    (lambda: L.AtrousConvolution1D(4, 3, atrous_rate=2,
                                   border_mode="same"), (10, 3)),
    (lambda: L.AveragePooling1D(2), (8, 3)),
    (lambda: L.AveragePooling3D((2, 2, 2)), (4, 4, 4, 2)),
    (lambda: L.MaxPooling1D(2), (8, 3)),
    (lambda: L.MaxPooling3D((2, 2, 2)), (4, 4, 4, 2)),
    (lambda: L.GlobalAveragePooling1D(), (6, 3)),
    (lambda: L.GlobalAveragePooling3D(), (4, 4, 4, 2)),
    (lambda: L.GlobalMaxPooling1D(), (6, 3)),
    (lambda: L.GlobalMaxPooling3D(), (4, 4, 4, 2)),
    (lambda: L.UpSampling1D(2), (5, 3)),
    (lambda: L.UpSampling3D((2, 2, 2)), (3, 3, 3, 2)),
    (lambda: L.ZeroPadding1D(2), (5, 3)),
    (lambda: L.ZeroPadding3D((1, 1, 1)), (3, 3, 3, 2)),
    (lambda: L.Cropping1D((1, 1)), (6, 3)),
    (lambda: L.Cropping3D(((1, 1), (1, 1), (1, 1))), (5, 5, 5, 2)),
    (lambda: L.LocallyConnected1D(4, 3), (8, 3)),
    (lambda: L.LocallyConnected2D(4, 3, 3), (6, 6, 2)),
    (lambda: L.SpatialDropout1D(0.3), (6, 3)),
    (lambda: L.SpatialDropout3D(0.3), (4, 4, 4, 2)),
    (lambda: L.GaussianDropout(0.3), (5,)),
    (lambda: L.SparseDense(6), (9,)),
    (lambda: L.LRN2D(), (6, 6, 4)),
    (lambda: L.ResizeBilinear(12, 10), (6, 5, 3)),
    (lambda: L.ShareConvolution2D(4, 3, 3, border_mode="same"), (6, 6, 2)),
    (lambda: L.Scale((5,)), (5,)),
    (lambda: L.CAdd((5,)), (5,)),
    (lambda: L.CMul((5,)), (5,)),
    (lambda: L.AddConstant(2.0), (5,)),
    (lambda: L.MulConstant(2.0), (5,)),
    (lambda: L.Power(2.0), (5,)),
    (lambda: L.Negative(), (5,)),
    (lambda: L.Square(), (5,)),
    (lambda: L.Sqrt(), (5,)),
    (lambda: L.Exp(), (5,)),
    (lambda: L.Identity(), (5,)),
    (lambda: L.Softmax(), (5,)),
    (lambda: L.SReLU(), (5,)),
    (lambda: L.RReLU(), (5,)),
    (lambda: L.HardTanh(), (5,)),
    (lambda: L.HardShrink(), (5,)),
    (lambda: L.SoftShrink(), (5,)),
    (lambda: L.Threshold(0.5), (5,)),
    (lambda: L.BinaryThreshold(0.5), (5,)),
    (lambda: L.ExpandDim(1), (5,)),
    (lambda: L.Squeeze(1), (1, 5)),
    (lambda: L.Narrow(1, 1, 3), (6,)),
    (lambda: L.GetShape(), (4, 3)),
]


def _ids():
    out = []
    for thunk, _ in CASES:
        try:
            out.append(type(thunk()).__name__)
        except Exception:
            out.append("broken")
    return out


@pytest.mark.parametrize("thunk,in_shape", CASES, ids=_ids())
def test_declared_shape_matches_forward(thunk, in_shape):
    layer = thunk()
    batch = 4
    declared = layer.compute_output_shape((None,) + tuple(in_shape))
    rng = jax.random.PRNGKey(0)
    params, state = layer.build(rng, (None,) + tuple(in_shape))
    if isinstance(layer, L.Embedding):
        x = np.random.RandomState(0).randint(0, 19, (batch,) + in_shape)
        x = x.astype(np.float32)
    else:
        x = np.random.RandomState(0).rand(*((batch,) + in_shape))
        x = x.astype(np.float32)
    y, _ = layer.call(params, state, x, training=False,
                      rng=jax.random.PRNGKey(1))
    actual = np.asarray(y).shape
    expect = tuple(batch if d is None else d for d in declared)
    assert actual == expect, (
        f"{type(layer).__name__}: declared {declared} -> {expect}, "
        f"forward produced {actual}")
